"""Distributed-tracing tests: trace-context wire format, backend
propagation, and the end-to-end fleet trace.

The acceptance path (ISSUE 3): a single-process cross-silo simulation over
the inmemory backend with 3 clients produces ONE ``export_fleet_trace()``
Perfetto JSON containing the server lane plus one lane per client, with
client ``train`` spans sharing the server round's ``trace_id``.
"""

import json
import queue
import threading
import time

import pytest

from fedml_tpu.core import telemetry as tel
from fedml_tpu.core.telemetry import trace_context as tc
from fedml_tpu.core.telemetry.fleet import FleetTelemetry
from fedml_tpu.core.distributed.communication.message import Message


class TestTraceparentFormat:
    def test_round_trip(self):
        ctx = tc.TraceContext(tc.new_trace_id(), parent_span_id=71, round_idx=4)
        assert tc.TraceContext.from_traceparent(ctx.to_traceparent()) == ctx

    def test_no_parent_and_no_round(self):
        ctx = tc.TraceContext(tc.new_trace_id())
        tp = ctx.to_traceparent()
        assert "-0000000000000000-" in tp and tp.endswith("--1")
        back = tc.TraceContext.from_traceparent(tp)
        assert back.parent_span_id is None and back.round_idx is None
        assert back == ctx

    @pytest.mark.parametrize("bad", [
        None,
        42,
        "",
        "00",
        "00-short-0000000000000001-0",
        "99-" + "a" * 32 + "-" + "0" * 16 + "-0",       # unknown version
        "00-" + "g" * 32 + "-" + "0" * 16 + "-0",       # non-hex trace id
        "00-" + "a" * 32 + "-xyz-0",                     # bad parent
        "00-" + "a" * 32 + "-" + "0" * 16 + "-notanint",
    ])
    def test_malformed_returns_none(self, bad):
        assert tc.TraceContext.from_traceparent(bad) is None

    def test_new_trace_id_shape(self):
        tid = tc.new_trace_id()
        assert len(tid) == 32
        int(tid, 16)  # hex
        assert tc.new_trace_id() != tid


class TestInjectExtract:
    def test_inject_noop_without_context(self):
        msg = Message(1, 1, 0)
        tc.set_current(None)
        tc.inject(msg)
        assert msg.get(Message.MSG_ARG_KEY_TELEMETRY) is None

    def test_inject_extract_round_trip(self):
        ctx = tc.TraceContext(tc.new_trace_id(), 9, 1)
        msg = Message(1, 1, 0)
        with tc.activated(ctx):
            tc.inject(msg)
        assert tc.extract(msg) == ctx

    def test_inject_preserves_existing_delta(self):
        msg = Message(1, 1, 0)
        msg.add_params(Message.MSG_ARG_KEY_TELEMETRY, {tc.DELTA_FIELD: {"rank": 1}})
        with tc.activated(tc.TraceContext(tc.new_trace_id(), 1, 0)):
            tc.inject(msg)
        header = msg.get(Message.MSG_ARG_KEY_TELEMETRY)
        assert header[tc.DELTA_FIELD] == {"rank": 1}
        assert tc.TRACEPARENT_FIELD in header

    def test_header_survives_to_json(self):
        """The reserved header is control-plane: it must ride every wire
        format, i.e. survive Message.to_json() (which strips the payload)."""
        msg = Message(1, 1, 0)
        with tc.activated(tc.TraceContext(tc.new_trace_id(), 2, 0)):
            tc.inject(msg)
        wire = json.loads(msg.to_json())
        assert tc.TRACEPARENT_FIELD in wire[Message.MSG_ARG_KEY_TELEMETRY]

    def test_extract_absent_header_is_none(self):
        assert tc.extract(Message(1, 1, 0)) is None

    def test_extract_malformed_bumps_counter(self):
        before = tel.get_telemetry().counter(tc.MALFORMED_COUNTER).value
        msg = Message(1, 1, 0)
        msg.add_params(Message.MSG_ARG_KEY_TELEMETRY, {tc.TRACEPARENT_FIELD: "not-a-traceparent"})
        assert tc.extract(msg) is None
        assert tel.get_telemetry().counter(tc.MALFORMED_COUNTER).value == before + 1

    def test_activated_restores_previous(self):
        outer = tc.TraceContext(tc.new_trace_id(), 1, 0)
        inner = tc.TraceContext(tc.new_trace_id(), 2, 1)
        with tc.activated(outer):
            with tc.activated(inner):
                assert tc.current() == inner
            assert tc.current() == outer
            with tc.activated(None):  # old-sender message clears the context
                assert tc.current() is None
            assert tc.current() == outer
        assert tc.current() is None


class _RecordingObserver:
    """Observer that records the trace context active at dispatch time."""

    def __init__(self):
        self.seen = queue.Queue()

    def receive_message(self, msg_type, msg):
        self.seen.put((msg_type, tc.current()))


class TestInMemoryBackendPropagation:
    def _mgr(self, run_id):
        from fedml_tpu.core.distributed.communication.inmemory.broker import InMemoryBroker
        from fedml_tpu.core.distributed.communication.inmemory.inmemory_comm_manager import (
            InMemoryCommManager,
        )

        InMemoryBroker.reset()
        return InMemoryCommManager(run_id, rank=0, size=2)

    def test_receive_loop_restores_and_clears_context(self):
        mgr = self._mgr("tp_prop")
        obs = _RecordingObserver()
        mgr.add_observer(obs)
        loop = threading.Thread(target=mgr.handle_receive_message, daemon=True)
        loop.start()
        try:
            ctx = tc.TraceContext(tc.new_trace_id(), 5, 2)
            with_header = Message("with", 1, 0)
            with tc.activated(ctx):
                mgr.send_message(with_header)  # rank 0 -> itself, via broker
            malformed = Message("malformed", 1, 0)
            malformed.add_params(Message.MSG_ARG_KEY_TELEMETRY, {tc.TRACEPARENT_FIELD: "junk"})
            mgr.broker.publish(0, malformed)
            absent = Message("absent", 1, 0)  # old sender: no header at all
            mgr.broker.publish(0, absent)

            got = [obs.seen.get(timeout=10) for _ in range(3)]
            assert got[0] == ("with", ctx)
            assert got[1] == ("malformed", None)  # tolerated, not raised
            assert got[2] == ("absent", None)     # and no stale inheritance
        finally:
            mgr.stop_receive_message()
            loop.join(timeout=10)
        assert tc.current() is None


class TestDeltaSnapshot:
    def test_cursor_and_thread_filter(self):
        t = tel.Telemetry(enabled=True)
        with t.span("a"):
            pass
        d1 = t.delta_snapshot(0)
        assert [r["name"] for r in d1["spans"]] == ["a"]
        with t.span("b"):
            pass
        d2 = t.delta_snapshot(d1["cursor"])
        assert [r["name"] for r in d2["spans"]] == ["b"]
        # a span recorded from another thread is filtered out by tid
        worker = threading.Thread(target=lambda: t.span("other").__enter__().__exit__(None, None, None))
        worker.start()
        worker.join()
        d3 = t.delta_snapshot(d2["cursor"], tid=threading.get_ident())
        assert [r["name"] for r in d3["spans"]] == []

    def test_json_safe_attrs(self):
        t = tel.Telemetry(enabled=True)
        with t.span("a", obj=object(), n=3):
            pass
        d = t.delta_snapshot(0)
        json.dumps(d)  # must be wire-able
        assert d["spans"][0]["attrs"]["n"] == 3

    def test_fleet_merge_rejects_junk(self):
        f = FleetTelemetry()
        assert not f.merge_client_delta(1, "not a dict")
        assert not f.merge_client_delta("rank?", {})
        assert f.rejected == 2
        assert f.merge_client_delta(1, {"spans": [{"bogus": True}], "counters": {"c": 1}})
        assert f.summary()["clients"]["1"]["spans_merged"] == 0
        assert f.summary()["clients"]["1"]["counters"] == {"c": 1}


class TestFleetTraceEndToEnd:
    def test_three_client_round_produces_fleet_trace(self, tmp_path):
        """ISSUE 3 acceptance: 3-client inmemory cross-silo run -> one fleet
        Perfetto JSON (server lane + 3 client lanes), client train spans
        sharing the server round's trace_id and nesting under round spans."""
        import fedml_tpu as fedml
        from fedml_tpu.arguments import default_config
        from fedml_tpu.core.distributed.communication.inmemory.broker import InMemoryBroker

        fleet_path = tmp_path / "fleet.json"
        n_clients, rounds = 3, 2

        def make_args(rank, role):
            over = dict(
                run_id="test_fleet_trace", rank=rank, role=role, backend="INMEMORY",
                scenario="horizontal", client_num_in_total=n_clients,
                client_num_per_round=n_clients, comm_round=rounds, epochs=1,
                batch_size=16, frequency_of_the_test=1, dataset="synthetic",
                model="lr", random_seed=0,
            )
            if role == "server":
                over["fleet_trace"] = str(fleet_path)
            return default_config("cross_silo", **over)

        def run_party(args, results, key):
            args = fedml.init(args)
            device = fedml.device.get_device(args)
            dataset, output_dim = fedml.data.load(args)
            model = fedml.model.create(args, output_dim)
            results[key] = fedml.FedMLRunner(args, device, dataset, model).run()

        t = tel.get_telemetry()
        was_enabled = t.enabled
        t.set_enabled(True)
        t.reset()
        try:
            InMemoryBroker.reset()
            results = {}
            threads = [threading.Thread(
                target=run_party, args=(make_args(0, "server"), results, "server"), daemon=True)]
            for rank in range(1, n_clients + 1):
                threads.append(threading.Thread(
                    target=run_party, args=(make_args(rank, "client"), results, f"c{rank}"), daemon=True))
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=600)
                assert not th.is_alive(), "fleet-trace cluster deadlocked"
            assert results["server"] is not None

            snap = t.snapshot()
            rounds_spans = [r for r in snap["spans"] if r["name"] == "server.round"]
            train_spans = [r for r in snap["spans"] if r["name"] == "client.train"]
            assert len(rounds_spans) == rounds
            assert len(train_spans) == rounds * n_clients
            trace_ids = {r.get("trace_id") for r in rounds_spans}
            assert len(trace_ids) == 1 and None not in trace_ids, rounds_spans
            round_seqs = {r["seq"] for r in rounds_spans}
            for r in train_spans:
                # client spans carry the server's trace_id ...
                assert r.get("trace_id") == next(iter(trace_ids)), r
                # ... and nest under a server.round span
                assert r.get("trace_parent") in round_seqs, (r, round_seqs)

            # one Perfetto JSON: server lane + one pid lane per client
            assert fleet_path.exists(), "export_fleet_trace did not run"
            doc = json.loads(fleet_path.read_text())
            lanes = {e["args"]["name"]: e["pid"] for e in doc["traceEvents"]
                     if e["ph"] == "M" and e["name"] == "process_name"}
            assert "server" in lanes
            for rank in range(1, n_clients + 1):
                assert f"client-{rank}" in lanes, lanes
            assert len({lanes[k] for k in lanes}) == n_clients + 1  # distinct pids
            # the client lanes actually contain the train spans
            by_pid = {}
            for e in doc["traceEvents"]:
                if e["ph"] == "X":
                    by_pid.setdefault(e["pid"], []).append(e["name"])
            for rank in range(1, n_clients + 1):
                assert "client.train" in by_pid.get(lanes[f"client-{rank}"], []), by_pid
            assert "server.round" in by_pid.get(lanes["server"], [])
            # spans appear in exactly one lane (thread-partitioned registry)
            assert "client.train" not in by_pid.get(lanes["server"], [])
        finally:
            t.reset()
            t.set_enabled(was_enabled)
            tc.set_current(None)


class TestCompressedUplinkTracePropagation:
    """ISSUE 10 satellite: the uplink compressor rewrites the model payload
    in place; the trace header must ride the SAME message untouched, and the
    server's handling spans must still land inside the round's trace."""

    def test_traceparent_survives_compressed_payload(self):
        import numpy as np

        from fedml_tpu.utils.compression import (
            decompress_comm_payload,
            is_comm_payload,
            make_comm_compressor,
        )

        class _Args:
            comm_compressor = "eftopk"
            comm_compressor_ratio = 0.5

        comp = make_comm_compressor(_Args())
        tree = {"w": np.arange(8.0, dtype=np.float32)}
        ctx = tc.TraceContext(tc.new_trace_id(), parent_span_id=3, round_idx=1)
        msg = Message("c2s", 1, 0)
        with tc.activated(ctx):
            msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, comp.compress_tree(tree))
            tc.inject(msg)
        # compressed payload present AND the header intact on the same message
        assert is_comm_payload(msg.get(Message.MSG_ARG_KEY_MODEL_PARAMS))
        assert tc.extract(msg) == ctx
        # the control-plane header rides the payload-stripping wire format too
        wire = json.loads(msg.to_json())
        assert tc.TRACEPARENT_FIELD in wire[Message.MSG_ARG_KEY_TELEMETRY]
        # and the payload still decompresses after the trip
        out = decompress_comm_payload(msg.get(Message.MSG_ARG_KEY_MODEL_PARAMS))
        assert out["w"].shape == (8,)

    def test_compressed_cluster_spans_nest_under_round(self):
        """2-client inmemory cross-silo run with eftopk uplink compression:
        client.compress fires per upload, server.decompress per receipt, and
        every one of them carries the round's trace — compression must not
        sever the trace chain."""
        import fedml_tpu as fedml
        from fedml_tpu.arguments import default_config
        from fedml_tpu.core.distributed.communication.inmemory.broker import InMemoryBroker

        n_clients, rounds = 2, 2

        def make_args(rank, role):
            return default_config(
                "cross_silo", run_id="test_compress_trace", rank=rank, role=role,
                backend="INMEMORY", scenario="horizontal",
                client_num_in_total=n_clients, client_num_per_round=n_clients,
                comm_round=rounds, epochs=1, batch_size=16,
                frequency_of_the_test=1, dataset="synthetic", model="lr",
                random_seed=0, comm_compressor="eftopk",
                comm_compressor_ratio=0.5,
            )

        def run_party(args, results, key):
            args = fedml.init(args)
            device = fedml.device.get_device(args)
            dataset, output_dim = fedml.data.load(args)
            model = fedml.model.create(args, output_dim)
            results[key] = fedml.FedMLRunner(args, device, dataset, model).run()

        t = tel.get_telemetry()
        was_enabled = t.enabled
        t.set_enabled(True)
        t.reset()
        try:
            InMemoryBroker.reset()
            results = {}
            threads = [threading.Thread(
                target=run_party, args=(make_args(0, "server"), results, "server"), daemon=True)]
            for rank in range(1, n_clients + 1):
                threads.append(threading.Thread(
                    target=run_party, args=(make_args(rank, "client"), results, f"c{rank}"), daemon=True))
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=600)
                assert not th.is_alive(), "compressed-uplink cluster deadlocked"
            assert results["server"] is not None

            snap = t.snapshot()
            round_spans = [r for r in snap["spans"] if r["name"] == "server.round"]
            compress = [r for r in snap["spans"] if r["name"] == "client.compress"]
            decompress = [r for r in snap["spans"] if r["name"] == "server.decompress"]
            assert len(round_spans) == rounds
            assert len(compress) == rounds * n_clients
            assert len(decompress) == rounds * n_clients
            for r in compress:
                assert r["attrs"]["kind"] == "eftopk", r
            trace_ids = {r.get("trace_id") for r in round_spans}
            assert len(trace_ids) == 1 and None not in trace_ids, round_spans
            round_seqs = {r["seq"] for r in round_spans}
            for r in compress + decompress:
                # the compressed hop keeps the round's trace_id ...
                assert r.get("trace_id") == next(iter(trace_ids)), r
                # ... and still nests under a server.round span
                assert r.get("trace_parent") in round_seqs, (r, round_seqs)
        finally:
            t.reset()
            t.set_enabled(was_enabled)
            tc.set_current(None)


class TestTelemetryLint:
    def test_reserved_key_containment_and_timing(self, capsys):
        """tools/check_telemetry.py: the reserved header literal appears only
        in trace_context.py, and no unmarked time.time() regressions."""
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "check_telemetry", os.path.join(repo, "tools", "check_telemetry.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main()
        assert rc == 0, capsys.readouterr().out

    def test_lint_catches_raw_literal(self, tmp_path):
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "check_telemetry", os.path.join(repo, "tools", "check_telemetry.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        bad = tmp_path / "offender.py"
        bad.write_text('KEY = "' + "__" + "telemetry" + '__"\n')
        assert mod.find_reserved_key_violations(str(tmp_path)) != []
