"""Bench regression sentinel (tools/bench_regress.py).

The sentinel walks the BENCH_MEASURED_*.json trajectory and compares each
headline key's newest occurrence against its most recent prior occurrence
(or a parsed BENCH_r0*.json baseline). These tests synthesize small
trajectories in tmp dirs and also assert the REAL repo trajectory is green —
the acceptance criterion is "flags a synthetically degraded artifact while
passing on the repo's actual history".
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import bench_regress  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, name, doc):
    (tmp_path / name).write_text(json.dumps(doc))


class TestFlatten:
    def test_numeric_leaves_dotted_and_bools_excluded(self):
        flat = bench_regress.flatten(
            {"a": {"b": 1, "ok": True}, "c": 2.5, "s": "text"})
        assert flat == {"a.b": 1.0, "c": 2.5}

    def test_ladder_value_is_metric_qualified(self):
        flat = bench_regress.flatten(
            {"metric": "llm_train_tokens_per_sec", "value": 100.0,
             "short_window": {"metric": "fedavg_rounds_per_hr", "value": 7.0}})
        assert flat["value:llm_train_tokens_per_sec"] == 100.0
        assert flat["short_window.value:fedavg_rounds_per_hr"] == 7.0
        assert "value" not in flat


class TestCompare:
    def test_degraded_artifact_is_flagged(self, tmp_path):
        _write(tmp_path, "BENCH_MEASURED_20260101T000000Z.json",
               {"fedavg_rounds_per_hr": 100.0, "mfu": 0.30})
        _write(tmp_path, "BENCH_MEASURED_20260102T000000Z.json",
               {"fedavg_rounds_per_hr": 50.0, "mfu": 0.31})
        report = bench_regress.compare(str(tmp_path), 0.10)
        regressed = {r["key"] for r in report["regressions"]}
        assert regressed == {"fedavg_rounds_per_hr"}
        row = report["regressions"][0]
        assert row["new"] == 50.0 and row["old"] == 100.0
        assert row["delta_pct"] == -50.0
        assert bench_regress.main(["--repo", str(tmp_path)]) == 1

    def test_lower_is_better_direction(self, tmp_path):
        _write(tmp_path, "BENCH_MEASURED_20260101T000000Z.json",
               {"serving_load_ttft_p99_s": 0.5})
        _write(tmp_path, "BENCH_MEASURED_20260102T000000Z.json",
               {"serving_load_ttft_p99_s": 1.5})
        report = bench_regress.compare(str(tmp_path), 0.10)
        assert [r["key"] for r in report["regressions"]] == \
            ["serving_load_ttft_p99_s"]

    def test_improvement_and_within_threshold_pass(self, tmp_path):
        _write(tmp_path, "BENCH_MEASURED_20260101T000000Z.json",
               {"fedavg_rounds_per_hr": 100.0, "agg_wall_s": 10.0})
        _write(tmp_path, "BENCH_MEASURED_20260102T000000Z.json",
               {"fedavg_rounds_per_hr": 95.0, "agg_wall_s": 8.0})
        report = bench_regress.compare(str(tmp_path), 0.10)
        assert report["compared"] == 2
        assert report["regressions"] == []
        assert bench_regress.main(["--repo", str(tmp_path)]) == 0

    def test_stage_isolated_runs_compare_per_key(self, tmp_path):
        # the key regressed two runs back; the newest artifact measured a
        # DIFFERENT stage and must not mask it
        _write(tmp_path, "BENCH_MEASURED_20260101T000000Z.json",
               {"decode_tokens_per_sec": 200.0})
        _write(tmp_path, "BENCH_MEASURED_20260102T000000Z.json",
               {"decode_tokens_per_sec": 90.0})
        _write(tmp_path, "BENCH_MEASURED_20260103T000000Z.json",
               {"resnet56_steps_per_sec": 5.0})
        report = bench_regress.compare(str(tmp_path), 0.10)
        assert [r["key"] for r in report["regressions"]] == \
            ["decode_tokens_per_sec"]

    def test_different_ladder_metrics_never_cross_compare(self, tmp_path):
        _write(tmp_path, "BENCH_MEASURED_20260101T000000Z.json",
               {"metric": "llm_train_tokens_per_sec", "value": 40000.0})
        _write(tmp_path, "BENCH_MEASURED_20260102T000000Z.json",
               {"metric": "fedavg_rounds_per_hr", "value": 8.0})
        report = bench_regress.compare(str(tmp_path), 0.10)
        assert report["compared"] == 0

    def test_baseline_fallback_for_single_occurrence(self, tmp_path):
        _write(tmp_path, "BENCH_r01.json",
               {"parsed": {"metric": "fedavg_rounds_per_hr", "value": 100.0}})
        _write(tmp_path, "BENCH_r02.json", {"parsed": None})
        _write(tmp_path, "BENCH_MEASURED_20260102T000000Z.json",
               {"metric": "fedavg_rounds_per_hr", "value": 40.0})
        report = bench_regress.compare(str(tmp_path), 0.10)
        assert len(report["regressions"]) == 1
        assert report["regressions"][0]["ref"] == "BENCH_r01.json"

    def test_nonheadline_keys_ignored(self, tmp_path):
        _write(tmp_path, "BENCH_MEASURED_20260101T000000Z.json",
               {"elapsed_s": 100.0, "n_devices": 8})
        _write(tmp_path, "BENCH_MEASURED_20260102T000000Z.json",
               {"elapsed_s": 900.0, "n_devices": 1})
        assert bench_regress.compare(str(tmp_path), 0.10)["compared"] == 0

    def test_empty_dir_is_clean_exit(self, tmp_path):
        report = bench_regress.compare(str(tmp_path), 0.10)
        assert report["newest"] is None
        assert bench_regress.main(["--repo", str(tmp_path)]) == 0


class TestRealTrajectory:
    @pytest.mark.skipif(
        not any(f.startswith("BENCH_MEASURED_") for f in os.listdir(REPO)),
        reason="no measured artifacts banked")
    def test_repo_history_is_green(self, capsys):
        assert bench_regress.main(["--repo", REPO]) == 0
        out = capsys.readouterr().out
        assert "bench_regress:" in out


class TestRenderTable:
    def test_table_marks_regressions(self, tmp_path):
        _write(tmp_path, "BENCH_MEASURED_20260101T000000Z.json",
               {"mfu": 0.30})
        _write(tmp_path, "BENCH_MEASURED_20260102T000000Z.json",
               {"mfu": 0.10})
        report = bench_regress.compare(str(tmp_path), 0.10)
        table = bench_regress.render_table(report)
        assert "REGRESS" in table
        assert "1 regression(s) over threshold" in table
