"""Cross-silo protocol tests over the in-memory backend.

This is the deterministic seam the reference lacks (SURVEY §4): the full
ONLINE/INIT/TRAIN/SYNC/FINISH state machine (§3.2) runs with server + N
clients as threads in one process. The reference's equivalent coverage is
the multi-process smoke script ``python/tests/cross-silo/run_cross_silo.sh``.
"""

import threading

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu.arguments import default_config
from fedml_tpu.core.distributed.communication.inmemory.broker import InMemoryBroker


def _make_args(run_id, rank, role, n_clients=2, rounds=2, scenario="horizontal", backend="INMEMORY", **extra):
    return default_config(
        "cross_silo",
        run_id=run_id,
        rank=rank,
        role=role,
        backend=backend,
        scenario=scenario,
        client_num_in_total=n_clients,
        client_num_per_round=n_clients,
        comm_round=rounds,
        epochs=1,
        batch_size=16,
        frequency_of_the_test=1,
        dataset="synthetic",
        model="lr",
        random_seed=0,
        **extra,
    )


def _run_party(args, results, key):
    args = fedml.init(args)
    device = fedml.device.get_device(args)
    dataset, output_dim = fedml.data.load(args)
    model = fedml.model.create(args, output_dim)
    runner = fedml.FedMLRunner(args, device, dataset, model)
    results[key] = runner.run()


def _run_cluster(run_id, scenario, backend, n_clients=2, rounds=2, **extra):
    """Server + N clients as threads over any backend; returns server metrics."""
    if backend == "INMEMORY":
        InMemoryBroker.reset()
    elif backend == "MQTT_S3":
        from fedml_tpu.core.distributed.communication.mqtt_s3.mqtt_transport import LocalMqttBroker

        LocalMqttBroker.reset(run_id)  # stale retained messages replay on subscribe
    results = {}
    threads = [
        threading.Thread(
            target=_run_party,
            args=(_make_args(run_id, 0, "server", n_clients, rounds, scenario, backend, **extra), results, "server"),
            daemon=True,
        )
    ]
    for rank in range(1, n_clients + 1):
        threads.append(
            threading.Thread(
                target=_run_party,
                args=(_make_args(run_id, rank, "client", n_clients, rounds, scenario, backend, **extra), results, f"client{rank}"),
                daemon=True,
            )
        )
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
        assert not t.is_alive(), f"cross-silo over {backend} deadlocked"
    metrics = results["server"]
    assert metrics is not None and "test_acc" in metrics
    assert metrics["round"] == rounds - 1
    assert np.isfinite(metrics["test_loss"])
    return metrics


@pytest.mark.parametrize("scenario", ["horizontal", "hierarchical"])
def test_cross_silo_round_trip(scenario):
    _run_cluster(f"test_cs_{scenario}", scenario, "INMEMORY")


def test_comm_compressor_full_ratio_uplink_parity():
    """``args.comm_compressor`` wires utils/compression.py into the C2S
    boundary. At eftopk ratio=1.0 the uplink round-trips bit-exactly, so the
    compressed run's final metrics must EQUAL the uncompressed run's — the
    parity guard for the comm wiring itself."""
    plain = _run_cluster("test_cs_comp_off", "horizontal", "INMEMORY")
    exact = _run_cluster(
        "test_cs_comp_on", "horizontal", "INMEMORY",
        comm_compressor="eftopk", comm_compressor_ratio=1.0)
    assert plain["test_loss"] == exact["test_loss"], (plain, exact)
    assert plain["test_acc"] == exact["test_acc"]


def test_comm_compressor_lossy_uplink_still_converges():
    """A genuinely sparsifying uplink (topk ratio 0.25) must still complete
    the run with finite metrics — the server transparently decompresses."""
    m = _run_cluster(
        "test_cs_comp_lossy", "horizontal", "INMEMORY",
        comm_compressor="topk", comm_compressor_ratio=0.25)
    assert np.isfinite(m["test_loss"])


def test_message_codec_roundtrip():
    import jax.numpy as jnp

    from fedml_tpu.core.distributed.communication.codec import message_from_bytes, message_to_bytes
    from fedml_tpu.core.distributed.communication.message import Message

    msg = Message(3, 1, 0)
    msg.add_params("num_samples", 42)
    params = {"layer": {"w": jnp.ones((4, 2), jnp.bfloat16), "b": jnp.zeros((2,))}, "meta": (jnp.ones(3), None)}
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, params)
    back = message_from_bytes(message_to_bytes(msg))
    assert back.get_type() == 3
    assert back.get_sender_id() == 1
    assert back.get("num_samples") == 42
    got = back.get(Message.MSG_ARG_KEY_MODEL_PARAMS)
    assert got["layer"]["w"].dtype.name == "bfloat16"
    np.testing.assert_allclose(np.asarray(got["layer"]["w"], dtype=np.float32), 1.0)
    assert got["meta"][1] is None


@pytest.mark.slow
def test_cross_silo_over_mqtt_s3():
    """Full round over the reference's DEFAULT backend: MQTT control plane
    (local broker) + object-store payloads — the octopus production path."""
    _run_cluster("test_cs_mqtt", "horizontal", "MQTT_S3")


@pytest.mark.slow
def test_backend_choice_does_not_change_numerics():
    """The transport must be semantically invisible: the same seeded run
    over INMEMORY and MQTT_S3 produces bit-identical final metrics."""
    a = _run_cluster("test_cs_det_a", "horizontal", "INMEMORY")
    b = _run_cluster("test_cs_det_b", "horizontal", "MQTT_S3")
    assert a["test_loss"] == b["test_loss"], (a, b)
    assert a["test_acc"] == b["test_acc"]
