"""Devperf (ISSUE 17): compiled-program registry capture, MFU fold parity
with bench's published arithmetic, the HBM sampler's thread hygiene, the
perf_report attribution invariant, and the mfu_collapse alert drill.

The capture tests run on a REAL jitted function: the AOT
``lower().compile()`` the wrapper performs must BE the one trace the jit
dispatcher would have spent (``jax.compiles.*`` stays at 1 across repeated
instrumented calls) — the zero-recompile contract every hot loop relies on.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import pytest

import bench
from fedml_tpu.core import telemetry as tel
from fedml_tpu.core.distributed import device_specs
from fedml_tpu.core.telemetry import devperf, flight_recorder, slo, tsdb
from tools import perf_report


def _instrumented_matmul(label, size=64, **kw):
    body = jax.jit(tel.track_compiles(
        lambda x: (x @ x).sum(), name=label))
    return (devperf.instrument(body, label, **kw),
            jnp.ones((size, size), jnp.float32))


# ---------------------------------------------------------------------------
# registry capture + zero-recompile
# ---------------------------------------------------------------------------

class TestInstrument:
    def test_capture_on_real_jitted_fn_zero_recompile(self):
        t = tel.get_telemetry()
        was = t.enabled
        t.set_enabled(True)
        try:
            fn, x = _instrumented_matmul("t_capture")
            vals = [float(fn(x)) for _ in range(4)]
            assert all(v == vals[0] for v in vals)
            # the AOT capture consumed the ONE trace jit would have spent
            assert tel.compile_count("t_capture") == 1
        finally:
            t.set_enabled(was)
        rec = devperf.get_registry().snapshot()["programs"]["t_capture"]
        assert rec["captured"] and rec["aot"]
        assert rec["flops_xla"] and rec["flops_xla"] > 0
        assert rec["bytes_accessed"] and rec["bytes_accessed"] > 0
        assert rec["op_intensity"] == pytest.approx(
            rec["flops_xla"] / rec["bytes_accessed"])
        assert rec["roofline_verdict"] in (devperf.VERDICT_COMPUTE,
                                           devperf.VERDICT_BANDWIDTH)
        assert rec["peak_flops_per_sec"] and rec["peak_flops_per_sec"] > 0
        assert rec["flops_source"] == devperf.FLOPS_SOURCE_XLA

    def test_disabled_returns_fn_unchanged(self, monkeypatch):
        monkeypatch.setenv("FEDML_DEVPERF", "0")
        f = jax.jit(lambda x: x + 1)
        assert devperf.instrument(f, "t_disabled") is f
        assert devperf.observe_step("t_disabled", 1.0) is None
        assert devperf.start_hbm_sampler() is None

    def test_caller_hint_beats_cost_analysis(self):
        fn, x = _instrumented_matmul("t_hint", flops_hint=123.0)
        float(fn(x))
        rec = devperf.get_registry().snapshot()["programs"]["t_hint"]
        assert rec["flops_source"] == devperf.FLOPS_SOURCE_ANALYTIC
        mfu = devperf.observe_step("t_hint", 0.5)
        assert mfu == pytest.approx(
            (123.0 / 0.5) / rec["peak_flops_per_sec"])


# ---------------------------------------------------------------------------
# MFU arithmetic parity with bench's published pipeline
# ---------------------------------------------------------------------------

class TestMfuParity:
    def test_fold_matches_bench_mfu_from_rate(self):
        """The registry fold and ``bench._mfu_from_rate`` are the SAME
        tokens/sec -> MFU arithmetic — the property the devperf_overhead
        bench stage guards end-to-end at 15%."""
        flops_per_token, tokens_per_step, steps, wall = 250.0, 512, 8, 0.4
        reg = devperf.get_registry()
        reg.register("t_parity", flops_per_token_hint=flops_per_token)
        reg.note_capture("t_parity", device_kind="unknown-chip",
                         flops_xla=None, bytes_accessed=None, memory=None,
                         aot=False)
        mfu = devperf.observe_step("t_parity", wall, steps=steps,
                                   tokens=steps * tokens_per_step)
        peak = device_specs.peak_flops_per_sec("unknown-chip")
        expected = bench._mfu_from_rate(
            tokens_per_sec=steps * tokens_per_step / wall,
            step_flops=flops_per_token * tokens_per_step,
            tokens_per_step=tokens_per_step,
            peak_flops_per_sec=peak)
        assert mfu == pytest.approx(expected, rel=1e-12)

    def test_peak_table_matches_bench_lookup(self):
        """bench's ``_chip_peak_tflops`` now IS device_specs (satellite 1):
        one table, no drift."""

        class _Dev:
            device_kind = "TPU v4"

        assert bench._chip_peak_tflops(_Dev(), 16) == pytest.approx(
            device_specs.peak_tflops("TPU v4", 16))
        assert device_specs.peak_tflops("v5p", 16) == pytest.approx(459.0)
        # unknown chips fall back to the modest CPU-CI peak, never 0
        assert device_specs.peak_tflops("cpu", 16) == pytest.approx(
            device_specs.UNKNOWN_PEAK_TFLOPS)
        assert bench._device_hbm_fallback("v5 lite") == 16 * 1024**3


# ---------------------------------------------------------------------------
# HBM sampler
# ---------------------------------------------------------------------------

class TestHbmSampler:
    def test_start_stop_without_thread_leak(self):
        stats = [("dev:0", {"bytes_in_use": 10.0, "peak_bytes_in_use": 12.0,
                            "bytes_limit": 100.0})]
        sampler = devperf.HbmSampler(interval_s=0.01, stats_fn=lambda: stats)
        sampler.start()
        sampler.start()  # idempotent
        assert sampler.running
        deadline = time.monotonic() + 5.0
        while sampler.samples < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sampler.samples >= 2
        sampler.stop()
        sampler.stop()  # idempotent
        assert not sampler.running
        assert all(t.name != "devperf-hbm" for t in threading.enumerate())
        hbm = devperf.get_registry().snapshot()["hbm"]
        assert hbm["dev:0"]["peak_bytes_in_use"] == pytest.approx(12.0)

    def test_sample_records_high_water_frac_gauge(self):
        store = tsdb.install()
        try:
            stats = [("dev:0", {"bytes_in_use": 10.0,
                                "peak_bytes_in_use": 30.0,
                                "bytes_limit": 100.0}),
                     ("dev:1", {"bytes_in_use": 50.0,
                                "peak_bytes_in_use": 80.0,
                                "bytes_limit": 100.0})]
            sampler = devperf.HbmSampler(interval_s=60.0,
                                         stats_fn=lambda: stats)
            assert sampler.sample_once() == 2
            # the gauge is the WORST device's high-water fraction
            assert store.last("devperf.hbm_high_water_frac") == \
                pytest.approx(0.8)
        finally:
            tsdb.reset()

    def test_prom_gauges_expose_hbm_and_programs(self):
        reg = devperf.get_registry()
        reg.register("t_prom", flops_hint=100.0)
        reg.note_capture("t_prom", device_kind="", flops_xla=None,
                         bytes_accessed=None, memory=None, aot=False)
        devperf.observe_step("t_prom", 0.5)
        reg.note_hbm("dev:0", {"bytes_in_use": 7.0, "peak_bytes_in_use": 9.0,
                               "bytes_limit": 10.0})
        gauges = {(name, tuple(sorted(labels.items())))
                  for name, labels, _v in devperf.prom_gauges()}
        assert ("device_mfu", (("program", "t_prom"),)) in gauges
        assert ("device_flops_per_sec", (("program", "t_prom"),)) in gauges
        assert ("device_hbm_bytes", (("device", "dev:0"),)) in gauges
        assert ("device_hbm_high_water_bytes", (("device", "dev:0"),)) in gauges


# ---------------------------------------------------------------------------
# round-time attribution (tools/perf_report.py)
# ---------------------------------------------------------------------------

class TestAttribution:
    def test_buckets_sum_to_round_wall(self):
        spans = {
            "fedavg.round": 10.0,
            "client.train": 6.0,      # compute
            "client.compress": 2.0,   # comm
            "fedavg.sample": 0.5,     # host
            "fedavg.eval": 0.5,       # host
            "agg.bucket": 3.0,        # wrapper detail: NOT bucketed
        }
        report = perf_report.attribute(spans, None)
        b = report["buckets_s"]
        assert b["compute"] == pytest.approx(6.0)
        assert b["comm"] == pytest.approx(2.0)
        assert b["host"] == pytest.approx(1.0)
        assert b["idle"] == pytest.approx(1.0)
        assert sum(b.values()) == pytest.approx(report["round_wall_s"],
                                                rel=1e-9)
        assert "agg.bucket" in report["unattributed_spans"]
        # over-attribution clamps idle at zero instead of going negative
        spans["client.train"] = 12.0
        assert perf_report.attribute(spans, None)["buckets_s"]["idle"] == 0.0

    def test_parse_and_join_with_devperf_snapshot(self):
        prom_text = "\n".join([
            '# TYPE fedml_span_seconds_total counter',
            'fedml_span_seconds_total{span="fedavg.round"} 20.0',
            'fedml_span_seconds_total{span="client.train"} 14.0',
            'fedml_span_count_total{span="fedavg.round"} 4',
            'fedml_other_metric 7',
        ])
        spans = perf_report.parse_span_seconds(prom_text)
        assert spans == {"fedavg.round": 20.0, "client.train": 14.0}
        reg = devperf.get_registry()
        reg.register("llm_train", flops_hint=1e9)
        reg.note_capture("llm_train", device_kind="", flops_xla=None,
                         bytes_accessed=None, memory=None, aot=False)
        devperf.observe_step("llm_train", 14.0)
        report = perf_report.attribute(
            spans, devperf.snapshot(),
            span_counts=perf_report.parse_span_counts(prom_text))
        assert report["rounds"] == pytest.approx(4)
        (top,) = report["top_programs"]
        assert top["label"] == "llm_train"
        assert top["device_seconds"] == pytest.approx(14.0)
        text = perf_report.render_text(report)
        assert "llm_train" in text and "compute" in text


# ---------------------------------------------------------------------------
# mfu_collapse alert drill: chaos-throttled step -> pending -> firing
# ---------------------------------------------------------------------------

class TestMfuCollapseAlert:
    def test_throttled_step_fires_alert_with_one_snapshot(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("FEDML_FR_DIR", str(tmp_path / "fr"))
        store = tsdb.install()
        try:
            row = next(r for r in slo.DEFAULT_PACKS["engine"]
                       if r["name"] == "mfu_collapse")
            eng = slo.SLOEngine([slo.SLOSpec(**row)], store=store,
                                front="test")
            # a ~1e4-FLOP program against a >=50ms throttled wall sits at
            # ~1e-7 MFU even vs the modest unknown-chip peak: two orders of
            # magnitude under the pack's 1e-5 collapse floor
            fn, x = _instrumented_matmul("t_chaos", size=16)
            with flight_recorder.installed(role="test"):
                for _ in range(4):
                    t0 = time.perf_counter()
                    float(fn(x))
                    time.sleep(0.05)  # the chaos throttle: device "stalled"
                    mfu = devperf.observe_step(
                        "t_chaos", time.perf_counter() - t0)
                    assert mfu is not None and mfu < 1e-6
                    eng.tick()
                st = eng.statusz()["slos"]["mfu_collapse"]
                assert st["state"] == slo.STATE_FIRING
                trans = [(t["from"], t["to"]) for t in eng.history]
                assert ("ok", "pending") in trans
                assert ("pending", "firing") in trans
                dumps = sorted((tmp_path / "fr").glob("fr_*.jsonl"))
                assert len(dumps) == 1, "exactly one auto-snapshot per firing"
            # instrumented chaos steps still never re-traced
            assert tel.compile_count("t_chaos") == 1
        finally:
            tsdb.reset()

    def test_hbm_high_water_breach_trips_pack_row(self):
        store = tsdb.install()
        try:
            row = next(r for r in slo.DEFAULT_PACKS["serving"]
                       if r["name"] == "hbm_high_water")
            eng = slo.SLOEngine([slo.SLOSpec(**row)], store=store,
                                front="test")
            stats = [("dev:0", {"bytes_in_use": 97.0,
                                "peak_bytes_in_use": 99.0,
                                "bytes_limit": 100.0})]
            sampler = devperf.HbmSampler(interval_s=60.0,
                                         stats_fn=lambda: stats)
            for _ in range(2):
                sampler.sample_once()
                eng.tick()
            assert eng.statusz()["slos"]["hbm_high_water"]["state"] == \
                slo.STATE_FIRING
        finally:
            tsdb.reset()
