"""The native C++ edge agent as a real network participant.

Reference: the Android client (android/fedmlsdk) joins the federation over
MQTT as its own process; here ``native/edge/build/edge_agent`` does the same
over the socket message plane — a HETEROGENEOUS round with one C++ edge and
one Python edge training under the same server proves the wire protocol,
topic scheme (cross_device/wan.py) and blob format (dense_model.h) are one
contract across languages.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EDGE_DIR = os.path.join(REPO, "native", "edge")
AGENT = os.path.join(EDGE_DIR, "build", "edge_agent")


def _ensure_built():
    if not os.path.exists(AGENT):
        subprocess.run(["make", "-C", EDGE_DIR], check=True, capture_output=True)
    return AGENT


@pytest.mark.slow
def test_cpp_and_python_edges_in_one_federation(tmp_path):
    from fedml_tpu.core.distributed.communication.mqtt_s3.object_store import LocalObjectStore
    from fedml_tpu.core.distributed.communication.mqtt_s3.socket_broker import SocketMqttBroker
    from fedml_tpu.cross_device.codec import dense_forward
    from fedml_tpu.cross_device.wan import EdgeDeviceAgent, ServerEdgeWAN
    

    _ensure_built()
    broker = SocketMqttBroker()
    store_root = tmp_path / "store"
    store = LocalObjectStore(str(store_root))
    dim, classes = 12, 3

    class Args:
        run_id = "hetero1"
        mqtt_socket = broker.address

    # edge 0: the native C++ agent as its own OS process
    cpp_edge = subprocess.Popen(
        [AGENT, "127.0.0.1", str(broker.port), Args.run_id, "0", "0",
         str(store_root), "synthetic", "256", "32", "0.1", "2", "256"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )

    # edge 1: a Python edge over the same plane, same blob format
    from fedml_tpu.cross_device.codec import dataset_to_bytes

    rng = np.random.RandomState(5)
    n = 192
    y1 = rng.randint(0, classes, n)
    x1 = rng.randn(n, dim).astype(np.float32) * 0.3
    x1[np.arange(n), y1 * (dim // classes)] += 2.5
    data_path = tmp_path / "edge1.bin"
    data_path.write_bytes(dataset_to_bytes(x1, y1, classes))

    from fedml_tpu.cross_device.native_bridge import NativeEdgeEngine

    eng = NativeEdgeEngine(data_path=str(data_path), train_size=n, batch_size=32,
                           learning_rate=0.1, epochs=2, dims=[dim, classes])
    py_edge = EdgeDeviceAgent(1, eng, Args(), store=store, sample_num=n)

    template = [{"w": np.zeros((dim, classes), np.float32),
                 "b": np.zeros(classes, np.float32)}]

    def test_fn(params):
        logits = dense_forward(params, x1)
        return {"test_acc": float((logits.argmax(-1) == y1).mean())}

    server = ServerEdgeWAN(template, [0, 1], Args(), store=store, test_fn=test_fn)
    try:
        metrics = server.run(rounds=2, timeout_s=120)
        assert metrics is not None and metrics["round"] == 1
        assert py_edge.rounds_trained == 2
        # the native edge's uploads really exist as blob files it wrote
        native_uploads = [f for f in os.listdir(store_root) if f.startswith("edge_0_") and "native" in f]
        assert len(native_uploads) == 2, sorted(os.listdir(store_root))
        # aggregated model is non-trivial (both parties' updates merged)
        agg = server.aggregator.template
        assert float(np.abs(agg[0]["w"]).sum()) > 0.0
        assert metrics["test_acc"] > 0.8, metrics
    finally:
        server.stop()
        py_edge.stop()
        if cpp_edge.poll() is None:
            # server.run sends finish; give the binary a beat to exit clean
            try:
                cpp_edge.wait(timeout=10)
            except subprocess.TimeoutExpired:
                cpp_edge.kill()
        out = cpp_edge.stdout.read() if cpp_edge.stdout else ""
        broker.stop()
        print("cpp edge output:", (out or "")[-1500:])
    assert cpp_edge.returncode == 0
