"""Native C++ data plane: shard format, prefetcher coverage + determinism."""

import numpy as np
import pytest

from fedml_tpu.data.native_loader import (
    NativeBatchLoader,
    shard_info,
    write_shard,
)

pytestmark = pytest.mark.skipif(
    not NativeBatchLoader.available(), reason="no C++ toolchain"
)


@pytest.fixture()
def shards(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8, 3)).astype(np.float32)
    y = rng.integers(0, 10, 64).astype(np.int32)
    xp, yp = str(tmp_path / "x.fdlp"), str(tmp_path / "y.fdlp")
    write_shard(xp, x)
    write_shard(yp, y)
    return xp, yp, x, y


def test_shard_roundtrip_info(shards):
    xp, yp, x, y = shards
    dt, dims = shard_info(xp)
    assert dt == np.float32 and dims == (64, 8, 3)
    dt, dims = shard_info(yp)
    assert dt == np.int32 and dims == (64,)


def test_epoch_covers_all_samples_shuffled(shards):
    xp, yp, x, y = shards
    loader = NativeBatchLoader([xp, yp], batch_size=16, seed=7)
    assert loader.batches_per_epoch == 4
    xs, ys = [], []
    for bx, by in loader.epoch():
        assert bx.shape == (16, 8, 3) and by.shape == (16,)
        xs.append(bx)
        ys.append(by)
    allx = np.concatenate(xs)
    ally = np.concatenate(ys)
    # all 64 samples exactly once, in a non-identity order, x/y aligned
    order = np.argsort(allx[:, 0, 0])
    ref_order = np.argsort(x[:, 0, 0])
    np.testing.assert_array_equal(allx[order], x[ref_order])
    np.testing.assert_array_equal(ally[order], y[ref_order])
    assert not np.array_equal(ally, y)  # shuffled
    loader.close()


def test_same_seed_same_stream_different_seed_differs(shards):
    xp, yp, x, y = shards
    a = NativeBatchLoader([xp, yp], batch_size=16, seed=3)
    b = NativeBatchLoader([xp, yp], batch_size=16, seed=3)
    c = NativeBatchLoader([xp, yp], batch_size=16, seed=4)
    _, (ax, ay) = a.next_batch()
    _, (bx, by) = b.next_batch()
    _, (cx, cy) = c.next_batch()
    np.testing.assert_array_equal(ax, bx)
    np.testing.assert_array_equal(ay, by)
    assert not np.array_equal(ay, cy)
    for l in (a, b, c):
        l.close()


def test_epochs_reshuffle(shards):
    xp, yp, _, _ = shards
    loader = NativeBatchLoader([xp, yp], batch_size=32, seed=1)
    e1 = np.concatenate([by for _, by in loader.epoch()])
    e2 = np.concatenate([by for _, by in loader.epoch()])
    assert sorted(e1.tolist()) == sorted(e2.tolist())
    assert not np.array_equal(e1, e2)
    loader.close()


def test_mismatched_shards_rejected(tmp_path):
    xp, yp = str(tmp_path / "a.fdlp"), str(tmp_path / "b.fdlp")
    write_shard(xp, np.zeros((10, 2), np.float32))
    write_shard(yp, np.zeros((11,), np.int32))
    with pytest.raises(RuntimeError, match="disagree"):
        NativeBatchLoader([xp, yp], batch_size=2)


def test_arraydataset_stream_roundtrip(tmp_path):
    from fedml_tpu.data.dataset import ArrayDataset

    rng = np.random.default_rng(2)
    ds = ArrayDataset(
        rng.normal(size=(48, 4)).astype(np.float32),
        rng.integers(0, 5, 48).astype(np.int32),
    )
    paths = ds.save_shards(str(tmp_path / "train"))
    seen = []
    for bx, by in ArrayDataset.stream(paths, batch_size=16, seed=9, epochs=1):
        assert bx.shape == (16, 4)
        seen.append(by)
    ally = np.concatenate(seen)
    assert sorted(ally.tolist()) == sorted(ds.y.tolist())
