"""Continuous batching (serving/continuous_batching.py): slotted decode
engine correctness against the reference ``generate()`` path, join/leave
at token boundaries, EOS, single-compile across admission mixes, and the
runner integration that replaces the window micro-batcher."""

import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core import telemetry as tel
from fedml_tpu.models.transformer import TransformerConfig, TransformerLM
from fedml_tpu.serving.continuous_batching import ContinuousBatchingEngine
from fedml_tpu.train.llm.generation import generate

CFG = TransformerConfig(
    vocab_size=89, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=64,
    max_seq_len=64, dtype=jnp.float32, remat=False, lora_rank=0,
)


@pytest.fixture(scope="module")
def params():
    model = TransformerLM(CFG)
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))["params"]


@pytest.fixture()
def engine(params):
    eng = ContinuousBatchingEngine(params, CFG, num_slots=2, chunk=4)
    yield eng
    eng.shutdown()


def _prompt(length, seed):
    return list(np.random.default_rng(seed).integers(1, CFG.vocab_size, length))


def test_engine_greedy_matches_generate(engine, params):
    """The keystone: for every prompt the slotted engine (per-row cache_idx
    scatter decode, requests interleaved across 2 slots) emits exactly the
    tokens the reference single-request ``generate()`` path emits."""
    prompts = [_prompt(n, i) for i, n in enumerate((5, 9, 3, 17))]
    handles = [engine.submit(p, 12) for p in prompts]
    for p, h in zip(prompts, handles):
        want = np.asarray(
            generate(params, CFG, jnp.asarray([p], jnp.int32), 12)
        )[0].tolist()
        assert h.result(timeout=120) == want


def test_engine_join_leave_more_requests_than_slots(engine):
    """6 requests through 2 slots: admission happens at token boundaries
    (freed slots re-admit from the FIFO) and every future completes."""
    handles = [engine.submit(_prompt(4 + i, 100 + i), 6 + i) for i in range(6)]
    outs = [h.result(timeout=120) for h in handles]
    assert [len(o) for o in outs] == [6 + i for i in range(6)]
    st = engine.stats()
    assert st["requests_done"] == 6
    assert st["slots_active"] == 0 and st["queue_depth"] == 0
    assert st["tokens_out"] == sum(len(o) for o in outs)


def test_engine_eos_truncates_like_generate(engine, params):
    """Engine output stops AT the first EOS token (inclusive), matching the
    reference stream up to that point; generate() instead fills the tail
    (static shapes), so compare the truncated prefix."""
    prompt = _prompt(5, 7)
    ref = np.asarray(
        generate(params, CFG, jnp.asarray([prompt], jnp.int32), 16)
    )[0].tolist()
    eos = ref[3]  # guaranteed to appear mid-stream
    got = engine.generate(prompt, 16, eos_id=eos)
    cut = ref.index(eos)
    assert got == ref[: cut + 1]
    # multi-EOS: any id in the tuple stops the stream
    got2 = engine.generate(prompt, 16, eos_id=(eos, CFG.vocab_size - 1))
    assert got2[-1] in (eos, CFG.vocab_size - 1)


def test_engine_sampled_same_seed_deterministic(engine):
    prompt = _prompt(6, 11)
    a = engine.generate(prompt, 10, temperature=0.8, seed=42)
    b = engine.generate(prompt, 10, temperature=0.8, seed=42)
    c = engine.generate(prompt, 10, temperature=0.8, seed=43)
    assert a == b
    assert len(c) == 10  # different seed still a full stream


def test_cb_executables_compile_once_across_admission_mixes(params):
    """The engine's whole point: one (cfg, B, C) step executable serves
    every mix of prompt lengths, temperatures, and stop tokens — per-row
    state is runtime data. A retrace here is the serving analogue of the
    int8 decode regression bench.py guards with compile counters."""
    eng = ContinuousBatchingEngine(params, CFG, num_slots=2, chunk=4)
    try:
        eng.generate(_prompt(4, 0), 5)  # warm: compiles admit + step once
        step0 = tel.compile_count("cb_step")
        admit0 = tel.compile_count("cb_admit")
        assert step0 >= 1 and admit0 >= 1
        hs = [
            eng.submit(_prompt(3, 1), 6),
            eng.submit(_prompt(19, 2), 9, temperature=0.7, seed=5),
            eng.submit(_prompt(8, 3), 4, eos_id=1),
        ]
        for h in hs:
            h.result(timeout=120)
        assert tel.compile_count("cb_step") == step0
        assert tel.compile_count("cb_admit") == admit0
    finally:
        eng.shutdown()


def test_engine_rejects_bad_requests_fast(engine):
    with pytest.raises(ValueError, match="at least one token"):
        engine.generate([], 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        engine.generate([1, 2], 0)
    with pytest.raises(ValueError, match="no decode room"):
        engine.generate(list(range(1, CFG.max_seq_len + 1)), 4)


def test_engine_budget_clamped_to_cache_capacity(engine):
    """A near-capacity prompt gets its stream clamped to the cache room
    left instead of scattering out of bounds (or erroring)."""
    prompt = _prompt(CFG.max_seq_len - 3, 21)
    out = engine.generate(prompt, 50)
    assert len(out) == 3  # S - P


def test_engine_queue_cap_and_shutdown_fail_fast(params):
    eng = ContinuousBatchingEngine(params, CFG, num_slots=1, chunk=2,
                                   max_queue=0)
    h = eng.submit([1, 2, 3], 4)
    with pytest.raises(RuntimeError, match="admission queue full"):
        h.result(timeout=5)
    eng.shutdown()
    h2 = eng.submit([1, 2, 3], 4)
    with pytest.raises(RuntimeError, match="shutting down"):
        h2.result(timeout=5)


def test_runner_serves_engine_and_exports_gauges(params):
    """The HTTP runner routes through the engine (micro-batcher skipped),
    /metrics exports the slot/queue gauges the autoscaler and load bench
    read, and /statusz carries the stats() snapshot."""
    from fedml_tpu.serving.fedml_inference_runner import FedMLInferenceRunner
    from fedml_tpu.serving.fedml_predictor import LLMPredictor

    class _Tok:  # minimal encode/decode for the predictor contract
        special_tokens = {}

        def encode(self, s):
            return [1 + (ord(c) % (CFG.vocab_size - 1)) for c in s] or [1]

        def decode(self, ids):
            return " ".join(str(i) for i in ids)

    pred = LLMPredictor(params, CFG, _Tok(), default_max_new_tokens=4,
                        continuous=True, num_slots=2, decode_chunk=2)
    assert pred.engine is not None
    runner = FedMLInferenceRunner(pred, port=0)
    assert runner.batcher is None  # engine replaces the window batcher
    port = runner.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=json.dumps({"prompt": "hi there", "max_new_tokens": 3}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert isinstance(out.get("text"), str) and out["text"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            metrics = r.read().decode()
        for g in ("serving_cb_slots_total", "serving_cb_slot_occupancy",
                  "serving_cb_queue_depth"):
            assert f"fedml_{g}" in metrics, g
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statusz", timeout=10
        ) as r:
            doc = json.loads(r.read())
        cb = doc["continuous_batching"]
        assert cb["slots_total"] == 2 and cb["requests_done"] >= 1
    finally:
        runner.stop()


def test_latency_percentiles_populated_after_traffic(engine):
    engine.generate(_prompt(4, 31), 6)
    pct = engine.latency_percentiles()
    assert pct["ttft_s"]["p50"] is not None and pct["ttft_s"]["p50"] > 0
    assert pct["tpot_s"]["p50"] is not None and pct["tpot_s"]["p50"] > 0


def test_check_serving_lint_clean_and_detects_regressions(tmp_path):
    """tools/check_serving.py: the repo's serving hot loops are span-
    instrumented (rc 0), and the lint actually fires when instrumentation
    is stripped or a registered hot loop disappears."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_serving", os.path.join(repo, "tools", "check_serving.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([]) == 0

    # synthetic tree: _admit_all lost its span, _step_chunk is gone,
    # replica_controller.py does not exist
    (tmp_path / "continuous_batching.py").write_text(
        "class ContinuousBatchingEngine:\n"
        "    def _admit_all(self):\n"
        "        return 1\n"
    )
    bad = mod.find_unspanned_hot_loops(str(tmp_path))
    msgs = [m for _, _, m in bad]
    assert any("_admit_all" in m and "no tel.timed" in m for m in msgs)
    assert any("_step_chunk" in m and "missing" in m for m in msgs)
    assert any("replica_controller.py" in m for m in msgs)
    assert mod.main([str(tmp_path)]) == 1
