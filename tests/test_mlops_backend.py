"""MLOps backend connectivity: MQTT telemetry uplink + REST log upload.

Reference parity: ``core/mlops/mlops_metrics.py`` (metric/status topics) and
``mlops_runtime_log_daemon.py`` (chunked POST) — here against the in-repo
LocalMLOpsCollector (VERDICT r1 missing #7)."""

import time

import pytest

import fedml_tpu.mlops as mlops
from fedml_tpu.core.distributed.communication.mqtt_s3.mqtt_transport import LocalMqttBroker
from fedml_tpu.mlops.backend import LocalMLOpsCollector, MLOpsUplink, http_log_sink


@pytest.fixture(autouse=True)
def _fresh():
    LocalMqttBroker.reset()
    mlops.MLOpsRuntime._instance = None
    yield
    mlops.MLOpsRuntime._instance = None
    LocalMqttBroker.reset()


class _Args:
    run_id = "mlops_test"
    using_mlops = True
    mlops_backend_mqtt = True
    log_file_dir = None
    enable_sys_perf = False  # no background sampler thread leaking records
    # into these collector-count assertions


def _wait(cond, timeout=10.0):
    deadline = time.time() + timeout
    while not cond() and time.time() < deadline:
        time.sleep(0.02)
    assert cond()


def test_metrics_status_events_reach_collector(tmp_path):
    args = _Args()
    # transport broker is keyed by run_id: collector must join the same one
    collector = LocalMLOpsCollector(str(tmp_path / "mlops"), args)
    try:
        args.log_file_dir = str(tmp_path / "logs")
        rt = mlops.MLOpsRuntime.get_instance()
        rt.init(args)
        assert rt.uplink is not None

        mlops.log({"test_acc": 0.91}, step=3)
        mlops.log_training_status("RUNNING", run_id="mlops_test")
        mlops.event("train", event_started=True, event_value="0")
        mlops.event("train", event_started=False, event_value="0")

        _wait(lambda: len(collector.metrics) >= 1 and len(collector.statuses) >= 1
              and len(collector.events) >= 2)
        assert collector.metrics[0]["test_acc"] == 0.91
        assert collector.metrics[0]["run_id"] == "mlops_test"
        assert collector.statuses[0]["status"] == "RUNNING"
        spans = {(e["name"], e["type"]) for e in collector.events if "name" in e}
        assert ("train", "event_started") in spans and ("train", "event_ended") in spans
        # spooled to jsonl for the dashboard
        assert (tmp_path / "mlops" / "metrics.jsonl").exists()
    finally:
        collector.stop()


def test_log_daemon_uploads_chunks_over_http(tmp_path):
    collector = LocalMLOpsCollector(str(tmp_path / "mlops"))
    try:
        log_path = tmp_path / "run.log"
        log_path.write_text("line one\nline two\n")
        from fedml_tpu.mlops.runtime_log import MLOpsRuntimeLogDaemon

        daemon = MLOpsRuntimeLogDaemon(
            str(log_path), "mlops_test", rank=1, sink=http_log_sink(collector.api_url)
        )
        assert daemon.poll_once() == 2
        with open(log_path, "a") as f:
            f.write("line three\n")
        assert daemon.poll_once() == 1
        assert len(collector.log_chunks) == 2
        first = collector.log_chunks[0]
        assert first["run_id"] == "mlops_test" and first["edge_id"] == 1
        assert first["logs"] == ["line one\n", "line two\n"]
    finally:
        collector.stop()


def test_uplink_failure_never_kills_the_run(tmp_path):
    args = _Args()
    args.log_file_dir = str(tmp_path / "logs")
    rt = mlops.MLOpsRuntime.get_instance()
    rt.init(args)
    rt.uplink.transport.publish = _raise  # sabotage
    mlops.log({"x": 1.0})  # must not raise
    assert rt.metrics


def _raise(*a, **k):
    raise ConnectionError("broker gone")


def test_jax_profiler_trace_capture(tmp_path):
    """SURVEY §5 tracing: a real jax.profiler trace is captured around a jit
    dispatch and lands on disk for XProf/TensorBoard."""
    import jax
    import jax.numpy as jnp

    args = _Args()
    args.mlops_backend_mqtt = False
    args.log_file_dir = str(tmp_path / "logs")
    rt = mlops.MLOpsRuntime.get_instance()
    rt.init(args)

    logdir = str(tmp_path / "trace")
    assert mlops.start_profiler_trace(logdir) is True
    assert mlops.start_profiler_trace(logdir) is False  # already running
    with mlops.profile_span("bench_matmul"):
        x = jnp.ones((64, 64))
        jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    out = mlops.stop_profiler_trace()
    assert out == logdir
    assert mlops.stop_profiler_trace() is None
    import glob

    assert glob.glob(logdir + "/**/*.xplane.pb", recursive=True), "no trace file captured"
    names = [r.get("name") for r in rt.records]
    assert "jax_profiler_trace" in names and "bench_matmul" in names
