"""SP algorithm-family tests: hierarchical, TurboAggregate, async,
decentralized, vertical FL (reference: simulation/sp/{hierarchical_fl,
turboaggregate,decentralized,classical_vertical_fl} + mpi/async_fedavg)."""

import numpy as np
import pytest

import fedml_tpu as fedml
from fedml_tpu.arguments import default_config


def _run(optimizer, rounds=2, **over):
    base = dict(
        backend="sp",
        model="lr",
        federated_optimizer=optimizer,
        comm_round=rounds,
        client_num_in_total=4,
        client_num_per_round=4,
        epochs=1,
        batch_size=16,
        frequency_of_the_test=1,
    )
    base.update(over)
    args = default_config("simulation", **base)
    args = fedml.init(args)
    device = fedml.device.get_device(args)
    dataset, output_dim = fedml.data.load(args)
    model_obj = fedml.model.create(args, output_dim)
    return fedml.FedMLRunner(args, device, dataset, model_obj).run()


def test_hierarchical_fl_learns():
    m = _run("HierarchicalFL", rounds=3, group_num=2, group_comm_round=2)
    assert m["test_acc"] > 0.3
    assert np.isfinite(m["test_loss"])


def test_turboaggregate_matches_fedavg_closely():
    """The ring's additive masks cancel exactly, so TA differs from plain
    FedAvg only by fixed-point quantization error."""
    m_ta = _run("TA", rounds=2, ta_group_num=2)
    m_avg = _run("FedAvg", rounds=2)
    assert abs(m_ta["test_acc"] - m_avg["test_acc"]) < 0.05
    assert abs(m_ta["test_loss"] - m_avg["test_loss"]) < 0.05


def test_async_fedavg_learns():
    m = _run("Async_FedAvg", rounds=4, client_num_per_round=2)
    assert m["test_acc"] > 0.3


def test_decentralized_dsgd_converges():
    import jax.numpy as jnp

    from fedml_tpu.simulation.sp.decentralized import FedML_decentralized_fl

    n_clients, N, d = 6, 40, 5
    rng = np.random.RandomState(0)
    w_true = rng.randn(d)
    x = rng.randn(n_clients, N, d).astype(np.float32)
    y = (x @ w_true > 0).astype(np.float32)

    def loss_fn(params, xb, yb):
        logit = xb @ params["w"]
        return jnp.mean(jnp.maximum(logit, 0) - logit * yb + jnp.log1p(jnp.exp(-jnp.abs(logit))))

    params0 = {"w": jnp.zeros((d,), jnp.float32)}
    args = type("A", (), {"b_symmetric": True, "iteration_number": 60, "learning_rate": 0.5, "batch_size": 4})()
    out = FedML_decentralized_fl(n_clients, (x, y), params0, loss_fn, args)
    assert out["loss_history"][-1] < out["loss_history"][0]
    # consensus: client params should be close to each other after mixing
    w_stack = np.asarray(out["params"]["w"])
    assert np.max(np.std(w_stack, axis=0)) < 0.2


def test_decentralized_pushsum_runs():
    import jax.numpy as jnp

    from fedml_tpu.simulation.sp.decentralized import FedML_decentralized_fl

    n_clients, N, d = 5, 20, 4
    rng = np.random.RandomState(1)
    x = rng.randn(n_clients, N, d).astype(np.float32)
    y = (x.sum(-1) > 0).astype(np.float32)

    def loss_fn(params, xb, yb):
        logit = xb @ params["w"]
        return jnp.mean((logit - yb) ** 2)

    params0 = {"w": jnp.zeros((d,), jnp.float32)}
    args = type("A", (), {"b_symmetric": False, "iteration_number": 30, "learning_rate": 0.1, "batch_size": 2})()
    out = FedML_decentralized_fl(n_clients, (x, y), params0, loss_fn, args)
    assert np.all(np.isfinite(np.asarray(out["params"]["w"])))
    assert out["loss_history"][-1] < out["loss_history"][0]


def test_vertical_fl_learns():
    from fedml_tpu.simulation.sp.classical_vertical_fl import VerticalFederatedLearning, VflFixture

    rng = np.random.RandomState(0)
    n, d_host, d_guest = 400, 4, 6
    x_host = rng.randn(n, d_host).astype(np.float32)
    x_guest = rng.randn(n, d_guest).astype(np.float32)
    w_h, w_g = rng.randn(d_host), rng.randn(d_guest)
    y = ((x_host @ w_h + x_guest @ w_g) > 0).astype(np.float32)

    vfl = VerticalFederatedLearning([d_host, d_guest], learning_rate=0.5)
    fixture = VflFixture(vfl)
    m = fixture.fit(
        [x_host[:300], x_guest[:300]], y[:300], [x_host[300:], x_guest[300:]], y[300:], epochs=10, batch_size=32
    )
    assert m["test_acc"] > 0.8
