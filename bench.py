"""Benchmark the two north-star workloads (BASELINE.md) on the attached chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu", ...}.

Headline metric: LLM full train-step throughput (tokens/sec) on a llama-family
~268M-parameter model, bf16, seq 1024 — the single-chip proxy for BASELINE
config 4 (Llama-2-7B LoRA; 7B itself does not fit one v5e chip's HBM, the
multi-chip sharding for it is validated by __graft_entry__.dryrun_multichip).
Secondary: ResNet-56/CIFAR-10 client local-SGD steps/sec (BASELINE config 2).

ARCHITECTURE (round 4, VERDICT r3 item 1): every stage runs in its OWN
subprocess that prints one JSON line —
    python bench.py --stage llm_pallas     (headline, runs FIRST)
    python bench.py --stage llm_xla
    python bench.py --stage decode / decode_int8   (fp vs weight-only int8)
    python bench.py --stage resnet         (+ measured FedAvg rounds/hr)
    python bench.py --stage cpu_llm / cpu_resnet   (host-only baselines)
    python bench.py --stage serving        (runs LAST)
so chip HBM is truly released between stages (the process exits) and one
stage's OOM cannot void the others. The orchestrator itself NEVER imports
jax: it only spawns stages, merges their JSON, and records failures into
``stages_failed``. rc is 0 whenever the headline stage produced a number.
A BENCH_MEASURED_* artifact is (re)written after EVERY successful stage,
so a mid-run tunnel death still leaves the completed stages in git.

Honesty guards (VERDICT round 1 found the old bench measured a platform
artifact — repeated identical dispatches were short-circuited; and on this
image's remote "axon" backend ``block_until_ready`` returns BEFORE remote
execution, so naive timing measures nothing):
  * every timed call is DISTINCT: params/opt-state chain call-to-call and
    each rep gets its own batch, so no execution can be deduplicated;
  * completion is forced by fetching the final chained SCALAR loss
    (``float(loss)`` — a 4-byte transfer the runtime cannot skip);
  * per-step time is the TWO-POINT marginal cost (12-rep chain minus 2-rep
    chain, /10), which cancels the constant tunnel round-trip latency;
  * MFU is reported from analytic FLOPs cross-checked against XLA's
    compiled.cost_analysis(), normalized to the chip's bf16 peak (JAX's
    default TPU matmul precision), and the script refuses to print a number
    whose implied MFU is >= 1.0 (physically impossible).

vs_baseline: same-workload torch-CPU implementation (the reference is torch
and publishes no numbers of its own — BASELINE.md; no CUDA exists here).
"""

from __future__ import annotations

import argparse
import datetime
import functools
import glob
import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))


def _p(msg: str) -> None:
    """Stage progress marker: a timed-out stage's killpg leaves only its
    stderr tail behind, so every expensive phase announces itself — the
    orchestrator's failure record then pins WHERE the hang was (array
    upload vs compile vs measurement), not just that 1500s elapsed."""
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)

# --- chip peak table (dense TFLOPS; bf16, f32≈bf16/2) ------------------------
# Promoted to fedml_tpu/core/distributed/device_specs.py (ISSUE 17) so the
# live devperf registry, the placement cost model, and this bench share ONE
# datasheet; imported lazily below because the orchestrator process never
# imports fedml_tpu (module docstring).

# flagship single-chip proxy geometry, shared by train/decode/serving stages
_LLM_SHAPE = dict(d_model=1024, n_layers=16, n_heads=16, d_ff=2752,
                  vocab=32000, seq=1024, bs=8)
# FEDML_BENCH_TINY=1: CI/dry-run geometry — exercises the REAL stage
# subprocess path (spawn, probe, fallback ladder, artifact write) in
# seconds on CPU; never a publishable number (the device field says cpu)
_TINY_LLM_SHAPE = dict(d_model=128, n_layers=2, n_heads=4, d_ff=256,
                       vocab=512, seq=128, bs=2)


def _llm_shape() -> dict:
    return _TINY_LLM_SHAPE if os.environ.get("FEDML_BENCH_TINY") == "1" else _LLM_SHAPE


def _chip_peak_tflops(device, dtype_bits: int) -> float:
    # unknown chip (CPU fallback runs in CI): device_specs assumes a modest
    # 2 TFLOPS so the MFU guard still triggers on absurd rates rather than
    # dividing by peak=0
    from fedml_tpu.core.distributed import device_specs

    return device_specs.peak_tflops(
        getattr(device, "device_kind", ""), dtype_bits)


def _cost_analysis_flops(lowered_compiled) -> float | None:
    try:
        ca = lowered_compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        f = float(ca.get("flops", 0.0))
        return f if f > 0 else None
    except Exception:
        return None


def _flash_effective_stats_mode(seq: int) -> str:
    """Kernel-truth stats mode for the bench geometry (imported lazily —
    the orchestrator process never imports jax/fedml_tpu)."""
    from fedml_tpu.ops.flash_attention import effective_stats_mode

    return effective_stats_mode(seq)


def _flash_effective_blocks(seq: int) -> str:
    """Kernel-truth block config for the bench geometry (env-resolved AND
    seq-clamped by the kernel's own resolver) — recorded in the artifact so
    a tuned headline names the config that actually ran."""
    from fedml_tpu.ops.flash_attention import effective_blocks

    return effective_blocks(seq)


def _timed_chain(step_once, reps_small: int = 2, reps_large: int = 12) -> float:
    """Marginal per-step seconds of a dependent chain.

    step_once(state_or_None, rep_index) -> state; the returned state must
    carry a scalar at key 'loss' (or be (params, opt, loss)) whose float()
    fetch forces remote completion. The two runs consume DISJOINT rep
    indices (small: [0, reps_small), large: [reps_small, +reps_large)), so
    no dispatch in the large chain repeats a (state, batch) pair the small
    chain or warmup already issued — the platform's dedup of repeated
    identical dispatches (module header) can't skip any timed step. Callers
    must therefore provision reps_small + reps_large distinct batches."""
    import time as _time

    def run(start: int, n: int) -> float:
        t0 = _time.perf_counter()
        state = None
        for r in range(start, start + n):
            state = step_once(state, r)
        loss = state[-1]
        float(loss)  # scalar fetch: cannot complete without executing the chain
        return _time.perf_counter() - t0

    t_small = run(0, reps_small)
    t_large = run(reps_small, reps_large)
    return (t_large - t_small) / (reps_large - reps_small)


class BenchIntegrityError(RuntimeError):
    """A measurement failed its own sanity guard — never retried, never
    published."""


class BenchProbeTimeout(TimeoutError):
    """The 3-minute backend probe timed out: the tunnel is DOWN, not flaky
    — never retried (socket read timeouts inside a bench ARE retried; on
    py>=3.10 socket.timeout is TimeoutError, so the probe needs its own
    class to stay distinguishable)."""


def _check_mfu(name: str, mfu: float) -> None:
    if not (0.0 < mfu < 1.0):
        raise BenchIntegrityError(
            f"{name}: implied MFU {mfu:.3f} is not in (0,1) — measurement is "
            "broken (platform short-circuit or wrong FLOP count); refusing to publish"
        )
    if not (0.01 <= mfu <= 0.7):
        print(f"warning: {name} MFU {mfu:.3f} outside typical 0.05-0.6 band", file=sys.stderr)


# --- MFU arithmetic (pure; pinned by tests/test_bench_mfu_arithmetic.py) -----
# The first chip number must be unimpeachable (VERDICT r4 next #9): these two
# functions ARE the published tokens/sec -> MFU pipeline, extracted so a test
# can pin them against hand-computed FLOP counts without a chip.

def _analytic_llm_step_flops(shape: dict, n_params: int) -> float:
    """Analytic train-step FLOPs for the llama-family proxy.

    Per token: 6*N_matmul (fwd 2N + bwd 4N, the standard convention) where
    N_matmul EXCLUDES the embedding table — the embed lookup is a gather,
    and counting its params as matmul FLOPs would inflate claimed MFU by
    ~12% at this geometry (the untied lm_head IS a matmul and stays
    counted). Plus causal attention 6*L*d*seq — derivation: QK^T and AV
    are seq^2*d MACs each per layer per sequence, so 4*seq^2*d FLOPs fwd,
    x3 with the backward = 12*seq^2*d, halved by the causal mask =
    6*seq^2*d per layer per sequence = 6*L*d*seq per token. Identical for
    both attention impls: the einsum path materializes masked [T,T] scores
    but wasted FLOPs don't count as useful model FLOPs."""
    tokens_per_step = shape["bs"] * shape["seq"]
    n_matmul = n_params - shape["vocab"] * shape["d_model"]
    return tokens_per_step * (
        6.0 * n_matmul + 6.0 * shape["n_layers"] * shape["d_model"] * shape["seq"]
    )


def _mfu_from_rate(tokens_per_sec: float, step_flops: float,
                   tokens_per_step: int, peak_flops_per_sec: float) -> float:
    """MFU from observed throughput: (FLOPs/token * tokens/sec) / peak."""
    return (step_flops / tokens_per_step) * tokens_per_sec / peak_flops_per_sec


# --- workload B: llama-268M full train step ----------------------------------

def _build_llm(attention_impl: str, remat: bool):
    """Flagship model + init params (shared by train/decode stages)."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.models.transformer import TransformerConfig, TransformerLM

    s = _llm_shape()
    cfg = TransformerConfig(
        vocab_size=s["vocab"], d_model=s["d_model"], n_layers=s["n_layers"],
        n_heads=s["n_heads"], n_kv_heads=s["n_heads"], d_ff=s["d_ff"],
        max_seq_len=s["seq"], remat=remat, lora_rank=0,
        attention_impl=attention_impl,
    )
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, cfg, params


def _bench_llm_tpu(reps: int = 10, attention_impl: str = "pallas", remat: bool = False,
                   bs: int | None = None, fsdp_shard: bool = False):
    import jax
    import jax.numpy as jnp
    import optax

    from fedml_tpu.parallel.fsdp import causal_lm_loss

    _p(f"llm bench: building model (attention={attention_impl} remat={remat}"
       f"{' fsdp_shard' if fsdp_shard else ''})")
    model, cfg, params = _build_llm(attention_impl, remat)
    s = _llm_shape()
    vocab, seq = s["vocab"], s["seq"]
    bs = int(bs or s["bs"])
    n_params = sum(x.size for x in jax.tree.leaves(params))
    _p(f"llm bench: {n_params/1e6:.0f}M params initialized")
    tx = optax.adamw(1e-4)

    if fsdp_shard:
        # OOM-recovery step 1 (orchestrator respawn, r7): ZeRO-3 the train
        # state over every local device via the GSPMD fsdp rules — the
        # measured geometry is unchanged, only the layout. Mask is all-ones
        # so the masked-mean loss equals the unmasked mean.
        from jax.sharding import Mesh

        from fedml_tpu.parallel.fsdp import make_fsdp_train_step

        n_dev = jax.device_count()
        mesh = Mesh(np.asarray(jax.devices()).reshape(n_dev), ("fsdp",))
        compile_step, init_fn = make_fsdp_train_step(
            lambda p, toks: model.apply({"params": p}, toks), tx, mesh,
            batch_axes=("fsdp",) if bs % n_dev == 0 else ())
        params, opt_state = init_fn(params)
        _mask = jnp.ones((bs, seq), jnp.float32)
        _fsdp_step = compile_step(params, opt_state)

        def step(params, opt_state, tokens):
            return _fsdp_step(params, opt_state, tokens, _mask)

        def _lower(p, o, t):
            return _fsdp_step.lower(p, o, t, _mask)
    else:
        opt_state = tx.init(params)

        # donate params + opt state: the real training loop's aliasing.
        # Without donation XLA double-buffers ~3.2GB of fp32 params + adam
        # moments (in + out live simultaneously), which is exactly the
        # headroom the bs=2x no-remat probe needs on a 16GB chip.
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(
                lambda p: causal_lm_loss(model.apply({"params": p}, tokens), tokens)
            )(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        _lower = step.lower

    def fresh_state():
        # donation consumes the buffers passed in, so every chain starts
        # from device-side copies and the pristine (params, opt_state)
        # survive for the next run. The copy cost is identical in the
        # 2-rep and 12-rep runs, so the two-point marginal cancels it.
        return (jax.tree.map(lambda x: x.copy(), params),
                jax.tree.map(lambda x: x.copy(), opt_state))

    rng = np.random.default_rng(0)
    # one distinct batch per DISPATCH — the disjoint-index chains consume
    # 0..reps+3, the profile step reps+4, the warmup reps+5: no two
    # dispatches anywhere in this stage see the same inputs
    batches = [jnp.asarray(rng.integers(0, vocab, (bs, seq)).astype(np.int32)) for _ in range(reps + 6)]
    _p(f"llm bench: {len(batches)} batches of ({bs},{seq}) on device; compiling step")

    compiled = _lower(params, opt_state, batches[0]).compile()
    xla_flops = _cost_analysis_flops(compiled)
    _p("llm bench: compile done; warmup step")
    float(step(*fresh_state(), batches[reps + 5])[2])  # warmup (excluded)
    _p("llm bench: warmup done; timing chain")

    def step_once(state, r):
        p, o = fresh_state() if state is None else (state[0], state[1])
        return step(p, o, batches[r])

    if os.environ.get("FEDML_BENCH_PROFILE") == "1":
        # capture an xplane trace for kernel-level analysis (tensorboard-
        # loadable); excluded from the timed chain. DISTINCT batch: the
        # warmup's exact dispatch would be deduped by the remote platform
        # (see module docstring) and trace no device execution
        trace_dir = os.path.join(_REPO, "bench_traces")
        with jax.profiler.trace(trace_dir):
            st = step(*fresh_state(), batches[reps + 4])
            float(st[2])
        print(f"profile trace written to {trace_dir}", file=sys.stderr)

    dt_step = _timed_chain(step_once, 2, reps + 2)

    tokens_per_step = bs * seq
    analytic_step_flops = _analytic_llm_step_flops(dict(s, bs=bs), n_params)
    if xla_flops is not None and not (0.3 <= xla_flops / analytic_step_flops <= 3.0):
        print(
            f"warning: XLA cost_analysis flops {xla_flops:.3e} disagrees with "
            f"analytic {analytic_step_flops:.3e}; using analytic", file=sys.stderr,
        )

    dev = jax.devices()[0]
    # a GSPMD-sharded step spreads the same FLOPs over every device, so the
    # MFU denominator is the MESH peak, not one chip's
    mesh_devices = jax.device_count() if fsdp_shard else 1
    peak = _chip_peak_tflops(dev, dtype_bits=16) * 1e12 * mesh_devices
    tokens_per_sec = tokens_per_step / dt_step
    mfu = _mfu_from_rate(tokens_per_sec, analytic_step_flops, tokens_per_step, peak)
    _check_mfu("llm", mfu)
    return {
        "tokens_per_sec": tokens_per_sec,
        "mfu": mfu,
        "attention_impl": attention_impl,
        "server_sharded": bool(fsdp_shard),
        "mesh_devices": mesh_devices,
        # which lse/delta lane layout the pallas kernels ran ("narrow" =
        # (block_q,1), "wide" = 128-lane broadcast) — from the kernel's own
        # shape-gated decision, not the env var, so the artifact can't claim
        # a layout the effective block size couldn't host
        "flash_stats_mode": (_flash_effective_stats_mode(seq)
                             if attention_impl == "pallas" else None),
        # the block config the flash calls resolved to (env-tuned by the
        # attn_micro sweep or the 128x128 default) — artifact provenance
        "flash_blocks": (_flash_effective_blocks(seq)
                         if attention_impl == "pallas" else None),
        "step_flops": analytic_step_flops,
        "n_params": n_params,
        "device": getattr(dev, "device_kind", str(dev)),
        "shape": dict(s, bs=bs),
    }


def _device_hbm_fallback(device_kind: str) -> int | None:
    """Datasheet HBM per JAX *device* (device_specs table), needed because
    some runtimes (the axon tunnel backend, measured r5) expose no
    memory_stats()['bytes_limit'] — without a capacity the memplan verdict
    silently degraded to null."""
    from fedml_tpu.core.distributed import device_specs

    return device_specs.device_hbm_bytes(device_kind)


def _bench_memplan():
    """Validate the shipped 7B fsdp=4 x tp=2 memory plan against the REAL
    device's HBM ceiling (VERDICT r4 next #6): tests/test_7b_memory_plan.py
    proves the analytic plan against the v5e CONSTANT; this stage reads the
    attached chip's own ``memory_stats()['bytes_limit']`` and records the
    comparison in the measured artifact. The plan math is metadata-only
    (eval_shape + shard_shape on a virtual 8-device CPU mesh — the stage env
    sets xla_force_host_platform_device_count=8). Chip interaction: the
    stats read, plus — only when the device exposes no bytes_limit (the
    axon backend, measured r5) — a one-shot allocation of plan_bytes on
    device for a direct fit/OOM verdict (one trivial compile + ~7.5GB
    alloc, freed immediately)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    from fedml_tpu.models.lora import lora_mask
    from fedml_tpu.models.transformer import TransformerConfig, TransformerLM
    from fedml_tpu.parallel.fsdp import param_shardings

    dev = jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)() or {}
    limit = stats.get("bytes_limit")
    limit_source = "memory_stats" if limit is not None else None
    kind = getattr(dev, "device_kind", str(dev))
    if limit is None and dev.platform == "tpu":
        fb = _device_hbm_fallback(kind)
        if fb is not None:
            limit, limit_source = fb, "device_kind_table"

    seq, global_bs = 1024, 8
    cfg = TransformerConfig.llama2_7b(
        max_seq_len=seq, lora_rank=8, remat=True, attention_impl="xla")
    model = TransformerLM(cfg)
    pshape = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 8), jnp.int32))["params"],
        jax.random.PRNGKey(0))
    cpu = jax.devices("cpu")
    if len(cpu) < 8:
        raise RuntimeError(
            f"memplan stage needs 8 virtual CPU devices, got {len(cpu)} — "
            "stage env must set --xla_force_host_platform_device_count=8")
    mesh = Mesh(np.asarray(cpu[:8]).reshape(4, 2), ("fsdp", "tp"))
    shard = param_shardings(pshape, mesh)
    param_bytes = sum(
        int(np.prod(sh.shard_shape(leaf.shape))) * leaf.dtype.itemsize
        for leaf, sh in zip(jax.tree.leaves(pshape), jax.tree.leaves(shard)))
    tx = optax.chain(optax.clip_by_global_norm(1.0),
                     optax.masked(optax.adamw(1e-4), lora_mask(pshape)))
    oshape = jax.eval_shape(tx.init, pshape)
    opt_bytes = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                    for l in jax.tree.leaves(oshape) if hasattr(l, "shape"))
    local_bs = global_bs // 4
    act_bytes = (cfg.n_layers * local_bs * seq * cfg.d_model * 2
                 + local_bs * seq * (cfg.vocab_size // 2) * 4)
    plan = param_bytes * 2 + opt_bytes + act_bytes  # params + grads + opt + acts
    out = {
        "plan_bytes_per_device": plan,
        "device_bytes_limit": limit,
        "device_bytes_limit_source": limit_source,
        "device_bytes_in_use": stats.get("bytes_in_use"),
        "device_kind": kind,
        # tri-state: True/False = measured verdict (from the bytes_limit
        # comparison — runtime-reported or the per-device-kind datasheet
        # table — or, when neither is available, from the direct allocation
        # probe below); None = no basis at all ("detail" names the basis)
        "memory_plan_validated": (bool(plan < limit) if limit is not None else None),
    }
    if limit_source == "device_kind_table":
        out["detail"] = (f"no memory_stats bytes_limit; capacity from "
                         f"device-kind table for {kind!r} "
                         f"({limit / 2**30:.0f} GiB datasheet HBM)")
    if limit is None and dev.platform == "tpu":
        # the axon device exposes no bytes_limit (measured r5) — get the
        # verdict DIRECTLY instead: allocate exactly plan_bytes on the chip
        # once. Success means the per-device plan fits real HBM; an OOM is a
        # measured False. One buffer, freed immediately; this stage runs
        # late in the ladder so a rejection cannot starve later stages the
        # way the r5 llm_xla OOM did.
        _p(f"memplan: no bytes_limit — allocating plan_bytes "
           f"({plan / 1e9:.2f} GB) on device for a direct verdict")
        try:
            buf = jax.jit(lambda: jnp.zeros((plan // 4,), jnp.float32))()
            float(buf[0])  # force materialization (module header: no
            # block_until_ready trust on this backend)
            out["memory_plan_validated"] = True
            out["detail"] = ("no bytes_limit exposed; validated by "
                            "allocating plan_bytes on device")
            del buf
        except Exception as e:  # noqa: BLE001 - OOM class varies by backend
            if "RESOURCE_EXHAUSTED" in repr(e) or "ResourceExhausted" in repr(e):
                out["memory_plan_validated"] = False
                out["detail"] = ("no bytes_limit exposed; plan_bytes "
                                 "allocation OOMed the device")
            else:
                out["detail"] = (f"no bytes_limit; direct allocation probe "
                                 f"errored non-OOM: {e!r}")
    elif limit is None:
        out["detail"] = "device exposes no memory_stats bytes_limit"
    return out


def _bench_llm_torch_cpu(shape, budget_s: float = 150.0) -> float | None:
    """Same-model torch-CPU train step; returns tokens/sec or None.

    Runs at bs=1 (per-token throughput on CPU is batch-insensitive at
    seq 1024 — the matmul shapes stay large — while bs=8 would take
    ~20 min/chain on this image's single core). The first step is warmup;
    the ratio comes from the warm step, which favors the baseline."""
    import torch
    import torch.nn as nn

    d, L, vocab, seq = shape["d_model"], shape["n_layers"], shape["vocab"], shape["seq"]
    bs = 1

    ff = shape["d_ff"]
    norm_cls = getattr(nn, "RMSNorm", nn.LayerNorm)

    class SwiGLU(nn.Module):
        # 3-matrix SwiGLU matching the JAX model's MLP FLOPs (gate/up/down)
        def __init__(self):
            super().__init__()
            self.gate = nn.Linear(d, ff, bias=False)
            self.up = nn.Linear(d, ff, bias=False)
            self.down = nn.Linear(ff, d, bias=False)

        def forward(self, x):
            return self.down(nn.functional.silu(self.gate(x)) * self.up(x))

    class Block(nn.Module):
        def __init__(self):
            super().__init__()
            self.ln1, self.ln2 = norm_cls(d), norm_cls(d)
            # MultiheadAttention stands in for RoPE attention (same matmul
            # FLOPs; rotary's elementwise cost is negligible)
            self.attn = nn.MultiheadAttention(d, 16, batch_first=True, bias=False)
            self.mlp = SwiGLU()

        def forward(self, x, mask):
            h = self.ln1(x)
            x = x + self.attn(h, h, h, attn_mask=mask, need_weights=False)[0]
            return x + self.mlp(self.ln2(x))

    class LM(nn.Module):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(vocab, d)
            self.blocks = nn.ModuleList([Block() for _ in range(L)])
            self.head = nn.Linear(d, vocab, bias=False)

        def forward(self, t):
            x = self.emb(t)
            mask = torch.triu(torch.full((t.shape[1], t.shape[1]), float("-inf")), 1)
            for b in self.blocks:
                x = b(x, mask)
            return self.head(x)

    try:
        model = LM()
        opt = torch.optim.AdamW(model.parameters(), lr=1e-4)
        tokens = torch.randint(0, vocab, (bs, seq))

        def one_step():
            opt.zero_grad()
            logits = model(tokens)
            loss = nn.functional.cross_entropy(
                logits[:, :-1].reshape(-1, vocab), tokens[:, 1:].reshape(-1)
            )
            loss.backward()
            opt.step()

        times = []
        t_start = time.perf_counter()
        for _ in range(2):
            t0 = time.perf_counter()
            one_step()
            times.append(time.perf_counter() - t0)
            if time.perf_counter() - t_start > budget_s:
                break
        if len(times) < 2:
            # only the cold step fit the budget: a cold-biased baseline would
            # overstate vs_baseline, so refuse to publish a ratio instead
            print("warning: torch-CPU LLM baseline got only a cold step; skipping ratio", file=sys.stderr)
            return None
        return bs * seq / min(times[1:])
    except Exception as e:
        print(f"warning: torch-CPU LLM baseline failed: {e}", file=sys.stderr)
        return None


def _bench_llm_decode_tpu(reps: int = 4, weight_quant: str = "none"):
    """Autoregressive decode throughput (serving path): tokens/sec of the
    KV-cache scan on the same llama model the train bench builds. Each rep
    uses a distinct prompt so the platform cannot dedupe executions.
    ``weight_quant="int8"`` measures the weight-only quantized path
    (serving/quant.py) — decode is HBM-bandwidth bound, so this is the
    direct measurement of the halved weight traffic."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.train.llm.generation import generate

    _, cfg, params = _build_llm("pallas", remat=False)
    if weight_quant == "int8":
        from fedml_tpu.serving.quant import quantize_model_int8

        _p("decode bench: quantizing weights to int8")
        cfg, params = quantize_model_int8(cfg, params)
    # prompt/new derived from the model's seq budget so the tiny dry-run
    # geometry (max_seq_len 128) fits: flagship stays 64 + 128
    s = _llm_shape()
    bs = 4
    P = min(64, s["seq"] // 2)
    new = min(128, s["seq"] - P)
    rng = np.random.default_rng(1)
    param_bytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(params) if hasattr(x, "nbytes")
    )

    def measure(n_new: int, n_reps: int) -> float:
        prompts = [
            jnp.asarray(rng.integers(0, cfg.vocab_size, (bs, P)).astype(np.int32))
            for _ in range(n_reps + 1)
        ]
        # warmup compiles prefill + the decode scan for this length bucket;
        # the trailing scalar fetch forces it to actually complete (module
        # header: axon's block_until_ready returns before remote execution)
        int(np.asarray(generate(params, cfg, prompts[0], n_new)[-1, -1]))
        t0 = time.perf_counter()
        outs = [generate(params, cfg, p, n_new) for p in prompts[1:]]
        # completion forced the same way the train stages do it — a 4-byte
        # fetch that depends on every full output. block_until_ready alone
        # measured DISPATCH on this backend (the r5 full ladder printed a
        # physically impossible 370k tok/s before this fetch existed). ONE
        # combined fetch, not one per rep: sequential per-rep fetches would
        # pay n_reps tunnel round-trips inside the window and deflate the rate.
        int(np.asarray(sum(o[-1, -1] for o in outs)))
        dt = time.perf_counter() - t0
        rate = bs * n_new * n_reps / dt
        _check_decode_bandwidth(rate, bs, param_bytes)
        return rate

    out = {"decode_tokens_per_sec": measure(new, reps), "bs": bs, "new": new,
           "weight_quant": weight_quant}
    _check_decode_compiles(weight_quant, out)
    # long decode: at new=128 the rate is partly fixed-cost bound (prefill +
    # tunnel round trip), which masks int8's halved weight traffic (measured
    # r5: 1.11x). A longer scan amortizes those costs so the quantized
    # comparison reflects the bandwidth story. Costs one extra scan-bucket
    # compile; skipped at tiny geometry where no longer bucket exists.
    new_long = min(512, cfg.max_seq_len - P)
    if new_long > new:
        _p(f"decode bench: long decode (new={new_long})")
        out["new_long"] = new_long
        out["decode_tokens_per_sec_long"] = measure(new_long, max(2, reps // 2))
        _check_decode_compiles(weight_quant, out)
    return out


def _check_decode_compiles(weight_quant: str, out: dict) -> None:
    """Compile-count regression guard for the decode stage (ISSUE 6): the
    scan must compile ONCE per (cfg, B, max_new bucket) LRU key. The r05
    int8 collapse (985 tok/s vs 370k bf16) was a per-call retrace class of
    failure — this guard keeps such a rate unpublished: trace counts come from the
    track_compiles counter inside the jitted body (fires at trace time
    only), keys from the generation LRU, and any excess is an integrity
    error, not a number."""
    from fedml_tpu.core.telemetry import compile_count
    from fedml_tpu.train.llm import generation

    n_keys = len([k for k in generation._COMPILED if k[0] == "decode"])
    n_traces = compile_count("decode_scan")
    out["decode_scan_compiles"] = n_traces
    out["decode_scan_keys"] = n_keys
    if n_traces > n_keys:
        raise BenchIntegrityError(
            f"decode[{weight_quant}]: the decode scan traced {n_traces}x for "
            f"{n_keys} executable key(s) — a per-call retrace (the r05 int8 "
            "collapse mechanism); refusing to publish a retrace-dominated rate"
        )


_FLASH_SWEEP = [(128, 128), (128, 256), (256, 256), (128, 512), (256, 512),
                (512, 512)]


def _bench_attn_micro(reps: int = 6):
    """Attention-only fwd+bwd microbench at the flagship geometry: the
    pallas flash kernels at several (block_q, block_k) configs vs the xla
    einsum path. Why: the r5 window measured the einsum+remat train step at
    MFU 0.261 — ~0.35 RAW hardware efficiency once remat's ~4/3 recompute
    is counted — against the flash headline's 0.299, implicating the
    kernel itself (not the surrounding step) as the MFU lever. This stage
    isolates it, and records the fastest flash config to
    .bench_runtime/flash_blocks (kernel-hash-scoped) so the NEXT window's
    headline runs the tuned kernel via FEDML_FLASH_BLOCK_Q/K."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.models.transformer import repeat_kv, xla_attention
    from fedml_tpu.ops.flash_attention import flash_attention

    s = _llm_shape()
    B, T, H = s["bs"], s["seq"], s["n_heads"]
    Dh = s["d_model"] // s["n_heads"]
    rng = np.random.default_rng(0)

    def mk():
        return jnp.asarray(
            rng.standard_normal((B, T, H, Dh)).astype(np.float32)
        ).astype(jnp.bfloat16)

    def time_impl(fn):
        # distinct q/k/v for EVERY dispatch — warmup, the 2-rep run AND the
        # reps-run each get their own tuples, so no call in either timed run
        # can be deduped against another (module header: the platform
        # short-circuits repeated identical dispatches). Allocated INSIDE
        # each attempt from the ADVANCING rng: a _retry_transient
        # re-invocation would otherwise re-dispatch the first attempt's
        # exact (function, inputs) pairs, which the platform dedups into a
        # bogus-fast retry timing (ADVICE r5 item 1)
        inputs = [(mk(), mk(), mk()) for _ in range(reps + 3)]
        # value_and_grad over a scalar readout runs fwd AND both bwd
        # kernels; the final scalar sum over every rep's value is the one
        # fetch that forces completion of the whole batch of dispatches
        step = jax.jit(jax.value_and_grad(
            lambda q, k, v: fn(q, k, v).astype(jnp.float32).mean(),
            argnums=(0, 1, 2)))
        float(step(*inputs[0])[0])  # compile + warmup (excluded)

        def run(start: int, n: int) -> float:
            t0 = time.perf_counter()
            vals = [step(*inputs[start + i])[0] for i in range(n)]
            float(sum(vals))
            return time.perf_counter() - t0

        t_small = run(1, 2)
        t_large = run(3, reps)
        dt = (t_large - t_small) / (reps - 2)
        if dt <= 0:
            # at micro scale the two-point marginal can go nonpositive on
            # noise (observed in CPU interpret mode); the large-run average
            # is a valid upper bound and keeps the comparison meaningful
            dt = t_large / reps
        return dt

    results: dict[str, float] = {}
    rejected: dict[str, str] = {}
    for bq, bk in _FLASH_SWEEP:
        if T % bq or T % bk:
            continue
        _p(f"attn micro: flash {bq}x{bk}")
        try:
            # through _retry_transient so one tunnel flake (or an OOM whose
            # buffers need reap time) gets the same same-config retry every
            # other measurement enjoys — only a REPEATED failure rejects
            dt = _retry_transient(
                time_impl, lambda q, k, v, bq=bq, bk=bk: flash_attention(
                    q, k, v, causal=True, block_q=bq, block_k=bk))
        except BenchIntegrityError:
            raise
        except Exception as e:  # noqa: BLE001 - a Mosaic rejection (or
            # persistent OOM) of ONE block config must not void the sweep:
            # only the smoked 128x128 default has proven acceptance, every
            # other config meets the real compiler for the first time here
            print(f"warning: flash {bq}x{bk} failed twice ({e!r}); "
                  "continuing sweep", file=sys.stderr)
            rejected[f"flash_{bq}x{bk}"] = repr(e)[:200]
            continue
        results[f"flash_{bq}x{bk}"] = round(dt * 1e3, 3)

    flash = {cfg: t for cfg, t in results.items() if cfg.startswith("flash_")}
    out = {
        "shape": {"bs": B, "seq": T, "heads": H, "d_head": Dh},
        "fwd_bwd_ms": results,
    }
    best = None
    if flash:
        best = min(flash, key=flash.get)
        out.update({
            "best_flash": best,
            "best_vs_128x128": round(flash.get("flash_128x128", 0.0)
                                     / flash[best], 3) if flash.get("flash_128x128") else None,
        })
        # the verdict is written BEFORE the einsum reference timing: the
        # flash sweep is complete at this point, and one einsum OOM (the
        # [T,T] score tensors are exactly what flash avoids) must not void
        # it (ADVICE r5 item 2). A CPU interpret-mode sweep says nothing
        # about Mosaic scheduling and must not steer the chip headline.
        if jax.devices()[0].platform == "tpu":
            bq, bk = best.removeprefix("flash_").split("x")
            os.makedirs(_BENCH_RUNTIME_DIR, mode=0o700, exist_ok=True)
            with open(os.path.join(_BENCH_RUNTIME_DIR, "flash_blocks"), "w") as f:
                f.write(f"{bq} {bk} {_kernel_hash()}")
            out["recorded"] = f"{bq}x{bk}"
    _p("attn micro: xla einsum")

    def einsum_attn(q, k, v):
        k2, v2 = repeat_kv(k, v, q.shape[2])
        return xla_attention(q, k2, v2, causal=True)

    try:
        # same per-config retry/rejection contract as the flash sweep: the
        # reference timing is a comparison denominator, not a gate
        dt = _retry_transient(time_impl, einsum_attn)
    except BenchIntegrityError:
        raise
    except Exception as e:  # noqa: BLE001 - einsum OOM/flake: record and move on
        print(f"warning: xla_einsum reference failed twice ({e!r}); "
              "flash verdict already recorded", file=sys.stderr)
        rejected["xla_einsum"] = repr(e)[:200]
    else:
        results["xla_einsum"] = round(dt * 1e3, 3)
        if best is not None:
            out["best_vs_einsum"] = round(results["xla_einsum"] / flash[best], 3)
    if rejected:
        out["rejected_configs"] = rejected
    return out


def _check_decode_bandwidth(rate: float, bs: int, param_bytes: int) -> None:
    """Integrity guard, mirroring the train stages' MFU<1 refusal: decode is
    weight-traffic bound — every decode step must stream the full param set
    from HBM, so steps/s * param_bytes cannot exceed HBM bandwidth. Allow 3x
    the v5e ~819 GB/s spec for headroom/other chips; beyond that the number
    is a measurement artifact (the r5 ladder published 370k tok/s when the
    timing captured only dispatch), not a throughput."""
    implied_bw = (rate / bs) * param_bytes
    if implied_bw > 3 * 819e9:
        raise BenchIntegrityError(
            f"decode rate {rate:.0f} tok/s implies {implied_bw / 1e12:.1f} TB/s "
            f"of weight traffic (params {param_bytes / 1e9:.2f} GB) — "
            "physically impossible; the timing did not capture execution"
        )


def _check_agg_bandwidth(label: str, cohort: int, gbps: float) -> None:
    """Integrity guard mirroring the decode stage's: every accumulator step
    must stream the whole bucket + read/write the f32 accumulator through
    HBM, so the implied bandwidth cannot exceed the chip's. Allow 3x the
    v5e ~819 GB/s spec for headroom/other chips; beyond that the timing
    captured dispatch (or the platform deduped the steps), not execution."""
    if gbps > 3 * 819.0:
        raise BenchIntegrityError(
            f"agg {label} K={cohort}: implied HBM bandwidth {gbps:.0f} GB/s is "
            "physically impossible — the timing did not capture execution"
        )


def _bench_agg(reps_cap: int = 16):
    """Bucketed-aggregation engine microbench: clients/sec of the
    donation-aware accumulator (core/aggregation/bucketed.py) across cohort
    sizes on the ResNet-56 and 268M-LLM parameter pytrees.

    Honesty contract (module header): the accumulator CHAINS (each step
    donates + consumes the previous accumulator) and every step draws fresh
    weights from an advancing host rng, so no two dispatches anywhere in
    the sweep see the same (function, inputs) pair; completion is forced by
    ONE combined scalar fetch over every rep's finalized tree per cohort.

    Memory: only ONE bucket of client trees is materialized (that is the
    engine's whole point — HBM high-water is O(bucket x model), not
    O(K x model)); larger cohorts reuse it with fresh weights, exactly the
    buffer pressure the production engine generates. LLM client payloads
    are bf16 (the flagship training dtype): 16 x 536MB + the f32
    accumulator fits a 16GB v5e where f32 clients would not. On non-TPU
    platforms the LLM pytree drops to the tiny geometry (recorded in
    agg_pytrees) so the CPU fallback completes in-budget."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.core import telemetry as tel
    from fedml_tpu.core.aggregation.bucketed import BucketedAggregator

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    bucket = int(os.environ.get("FEDML_AGG_BUCKET", "16"))
    cohorts = (8, 64, 257, 512)
    eng = BucketedAggregator(bucket)  # fresh engine: clean trace counters
    rng = np.random.default_rng(7)

    def make_clients(base, dtype):
        # one bucket of DISTINCT client trees (deterministic per-client
        # perturbation; setup cost, untimed), then the base is dropped
        return tuple(
            jax.jit(lambda t, i=i: jax.tree.map(
                lambda x: (x.astype(jnp.float32) + (i + 1) * 1e-4).astype(dtype), t))(base)
            for i in range(bucket)
        )

    def build_resnet():
        from fedml_tpu.models.resnet import ResNetCifar

        model = ResNetCifar(depth=56, num_classes=10)
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))["params"]
        return params, jnp.float32, "flagship"

    def build_llm():
        from fedml_tpu.models.transformer import TransformerConfig, TransformerLM

        s = _llm_shape() if on_tpu else _TINY_LLM_SHAPE
        cfg = TransformerConfig(
            vocab_size=s["vocab"], d_model=s["d_model"], n_layers=s["n_layers"],
            n_heads=s["n_heads"], n_kv_heads=s["n_heads"], d_ff=s["d_ff"],
            max_seq_len=s["seq"], remat=False, lora_rank=0, attention_impl="xla")
        params = TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
        geometry = "flagship" if s is _LLM_SHAPE else "tiny"
        return params, jnp.bfloat16, geometry

    clients_per_sec: dict = {}
    hbm_gbps: dict = {}
    pytrees_meta: dict = {}
    for label, build in (("resnet56", build_resnet), ("llm268m", build_llm)):
        _p(f"agg bench: building {label} pytree")
        base, client_dtype, geometry = build()
        n_params = sum(x.size for x in jax.tree.leaves(base))
        clients = make_clients(base, client_dtype)
        del base
        bucket_bytes = bucket * sum(x.nbytes for x in jax.tree.leaves(clients[0]))
        acc_bytes = 4 * n_params  # the running accumulator is always f32
        pytrees_meta[label] = {
            "n_params": int(n_params), "client_dtype": str(jnp.dtype(client_dtype)),
            "geometry": geometry,
        }

        def fresh_weights(n_real: int) -> np.ndarray:
            w = np.abs(rng.standard_normal(bucket)).astype(np.float32) + 0.1
            w[n_real:] = 0.0  # zero-weight padding of the ragged tail
            # host weights: the ENGINE does the upload at its comm boundary
            # (booked as comm.host_to_device_bytes — visible in --trace runs),
            # exactly what production rounds pay per bucket
            return w

        def one_rep(k: int):
            acc = None
            for ib in range(-(-k // bucket)):
                n_real = min(bucket, k - ib * bucket)
                acc = eng.accumulate_bucket(acc, clients, fresh_weights(n_real))
            fin = eng.finalize(acc, clients[0])
            # keep only a scalar handle per rep: the finalized model's
            # buffers free as soon as the handle's slice executes
            return jnp.ravel(jax.tree.leaves(fin)[0])[0]

        # warmup compiles the whole chain (first-bucket step, steady-state
        # donated step, finalize) ONCE — the signature never mentions the
        # cohort size, so every cohort below reuses these executables
        _p(f"agg bench: {label} warmup ({n_params / 1e6:.1f}M params)")
        float(one_rep(2 * bucket + 1))

        per_cohort: dict = {}
        per_cohort_bw: dict = {}
        for k in cohorts:
            nb = -(-k // bucket)
            # big pytrees cap reps at 2 (each rep's finalized tree briefly
            # coexists with the bucket); small ones use more for stability
            reps = 2 if acc_bytes > 100e6 else max(2, min(reps_cap, 256 // k))
            _p(f"agg bench: {label} K={k} ({nb} buckets x {reps} reps)")
            t0 = time.perf_counter()
            scalars = [one_rep(k) for _ in range(reps)]
            float(sum(scalars))  # ONE combined fetch forces every rep
            dt = time.perf_counter() - t0
            rate = k * reps / dt
            gbps = reps * nb * (bucket_bytes + 2 * acc_bytes) / dt / 1e9
            _check_agg_bandwidth(label, k, gbps)
            per_cohort[str(k)] = round(rate, 1)
            per_cohort_bw[str(k)] = round(gbps, 2)
        clients_per_sec[label] = per_cohort
        hbm_gbps[label] = per_cohort_bw
        del clients

    # per-span roll-up of the engine's own instrumentation (agg.bucket /
    # agg.finalize counts + totals) — rides the artifact so bench_watch.sh
    # can surface where the aggregation wall time went without a trace file
    agg_span_summary = {
        k: {"count": v["count"], "total_ms": round(v["total_ms"], 1),
            "max_ms": round(v["max_ms"], 2)}
        for k, v in tel.snapshot()["span_stats"].items()
        if k.startswith("agg.")
    }
    ckpt_enqueue_ms, resume_verified = _bench_round_checkpoint()
    return {
        "agg_clients_per_sec": clients_per_sec,
        "agg_hbm_gbps": hbm_gbps,
        "agg_bucket_size": bucket,
        "agg_cohorts": list(cohorts),
        "agg_pytrees": pytrees_meta,
        # 2 jit traces per pytree (first-bucket + steady-state), shared by
        # ALL cohort sizes — the in-artifact proof of the single-compile
        # contract the tier-1 regression test pins
        "agg_accum_traces": eng.accum_traces,
        "agg_span_summary": agg_span_summary,
        "ckpt_enqueue_ms": ckpt_enqueue_ms,
        "resume_verified": resume_verified,
        "device": getattr(dev, "device_kind", str(dev)),
    }


def _bench_round_checkpoint(rounds: int = 4):
    """Durable-round-state cost rider on the agg stage: the server enqueues
    an async checkpoint at every round boundary (core/resilience), so the
    enqueue must be effectively free next to aggregation itself. Times
    ``RoundStateStore.save_round(wait=False)`` on the ResNet-56 pytree and
    guards the best enqueue under 5 ms — past that the "async" save is
    blocking the round loop and resilience is no longer a rider. Then proves
    the whole durability story end to end: wait for the writer, resume from
    the watermark, and require the restored tree bit-identical
    (``resume_verified`` in the artifact; tools/bench_watch.sh surfaces it)."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from fedml_tpu.core.resilience import RoundStateStore
    from fedml_tpu.models.resnet import ResNetCifar

    model = ResNetCifar(depth=56, num_classes=10)
    params = jax.device_get(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))["params"]
    )
    tmp = tempfile.mkdtemp(prefix="bench_round_ckpt_")
    try:
        store = RoundStateStore(tmp)
        enqueue_ms = []
        for r in range(rounds):
            t0 = time.perf_counter()
            store.save_round(r, {"model": params}, cohort=[1, 2, 3], wait=False)
            enqueue_ms.append((time.perf_counter() - t0) * 1e3)
            # drain between reps (untimed): back-to-back enqueues would hit
            # the one-in-flight drop path and time nothing
            store.wait()
        best_ms = min(enqueue_ms)
        if best_ms >= 5.0:
            raise BenchIntegrityError(
                f"round-state enqueue {best_ms:.2f} ms >= 5 ms — the async "
                "checkpoint is blocking the round loop; refusing to publish"
            )
        store.close()
        reopened = RoundStateStore(tmp)
        template = jax.tree.map(np.zeros_like, params)
        rs = reopened.resume(template={"model": template})
        ok = rs is not None and rs.round_idx == rounds - 1 and all(
            np.array_equal(a, b) for a, b in zip(
                jax.tree.leaves(params), jax.tree.leaves(rs.state["model"]))
        )
        reopened.close()
        if not ok:
            raise BenchIntegrityError(
                "round-state resume is not bit-identical to the saved tree"
            )
        return round(best_ms, 3), True
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_agg_sharded(rounds: int = 4):
    """Mesh-parallel server round (core/aggregation/sharded.py) vs the
    single-device engine on the SAME cohort: per-device HBM high-water for
    accumulator + params + optimizer state, round throughput, and the
    ingestion-overlap efficiency of the double-buffered per-shard stream.

    Honesty contract: both engines consume identical (weight, tree) pairs
    with identical per-round weights, and end-of-run parity of the global
    params is an INTEGRITY GUARD (BenchIntegrityError), not a footnote. The
    headline HBM ratio is the analytic layout model — accumulator + params
    + optimizer state + one in-flight bucket + the finalized view, the
    terms the engine actually holds across a round — because CPU devices
    expose no memory_stats; where the platform reports peak_bytes_in_use
    the measured per-device peaks ride along, and hbm_source names which
    basis backed the ratio. Zero recompiles across rounds is enforced via
    the engine's trace-time counters, and the overlap measurement forces
    the serial reference by BLOCKING each bucket's per-shard transfer
    before its accumulation dispatches — the exact latency the
    double-buffered loop hides."""
    import types

    import jax
    import jax.numpy as jnp

    from fedml_tpu.core import telemetry as tel
    from fedml_tpu.core.aggregation.bucketed import BucketedAggregator
    from fedml_tpu.core.aggregation.server_optimizer import FedOptServer
    from fedml_tpu.core.aggregation.sharded import (
        ShardedBucketedAggregator,
        ShardedFedOptServer,
    )
    from fedml_tpu.core.distributed import mesh as dmesh

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    spec = os.environ.get(dmesh.SERVER_MESH_ENV) or "auto"
    dmesh.configure_server_mesh(spec=spec)
    mesh = dmesh.server_mesh()
    if mesh is None:
        # single-device host: the orchestrator respawns this stage once on
        # the virtual 8-CPU mesh (layout/overlap/parity are platform-
        # independent); this record is what triggers that respawn
        return {"skipped": f"single-device {dev.platform} host — no server mesh",
                "device": getattr(dev, "device_kind", str(dev))}

    bucket = int(os.environ.get("FEDML_AGG_BUCKET", "8"))
    k = 3 * bucket + 1  # ragged tail exercises the zero-weight pad path

    from fedml_tpu.models.transformer import TransformerConfig, TransformerLM

    s = _llm_shape() if on_tpu else _TINY_LLM_SHAPE
    geometry = "flagship" if s is _LLM_SHAPE else "tiny"
    cfg = TransformerConfig(
        vocab_size=s["vocab"], d_model=s["d_model"], n_layers=s["n_layers"],
        n_heads=s["n_heads"], n_kv_heads=s["n_heads"], d_ff=s["d_ff"],
        max_seq_len=s["seq"], remat=False, lora_rank=0, attention_impl="xla")
    client_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    # one dtype end to end (bf16 on TPU — the flagship broadcast dtype; f32
    # on CPU so the parity guard can pin a tight tolerance)
    params = jax.tree.map(lambda x: x.astype(client_dtype), params)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    param_bytes = sum(x.nbytes for x in jax.tree.leaves(params))
    _p(f"agg_sharded bench: {n_params / 1e6:.1f}M params ({geometry}), "
       f"{k} clients, bucket {bucket}")

    # one bucket of DISTINCT client trees (deterministic per-client
    # perturbation; setup cost, untimed) — larger cohorts cycle it with
    # fresh weights, the engine's production buffer pressure
    clients = tuple(
        jax.jit(lambda t, i=i: jax.tree.map(
            lambda x: (x.astype(jnp.float32) + (i + 1) * 1e-4).astype(client_dtype), t))(params)
        for i in range(bucket)
    )
    client_bytes = sum(x.nbytes for x in jax.tree.leaves(clients[0]))
    rng = np.random.default_rng(11)
    round_w = [np.abs(rng.standard_normal(k)).astype(np.float32) + 0.1
               for _ in range(rounds)]

    def pairs_for(r, pool):
        return [(float(round_w[r][i]), pool[i % bucket]) for i in range(k)]

    args_ns = types.SimpleNamespace(server_optimizer="adam", server_lr=0.05)

    # --- unsharded reference: whole accumulator + FedOpt state on device 0
    _p("agg_sharded bench: unsharded reference rounds")
    eng_u = BucketedAggregator(bucket)
    srv_u = FedOptServer(args_ns, params)
    g_u = params
    g_u = srv_u.apply(g_u, eng_u.aggregate(pairs_for(0, clients)))  # warmup round
    jax.block_until_ready(g_u)
    t0 = time.perf_counter()
    for r in range(1, rounds):
        g_u = srv_u.apply(g_u, eng_u.aggregate(pairs_for(r, clients)))
    jax.block_until_ready(g_u)
    unshard_rate = k * (rounds - 1) / (time.perf_counter() - t0)
    opt_bytes = sum(int(l.nbytes) for l in jax.tree.leaves(srv_u.state)
                    if hasattr(l, "nbytes"))
    # what the unsharded round actually holds on ONE device: f32 accumulator
    # + global params + finalized average + optimizer state + one bucket
    unsharded_peak = (4 * n_params + 2 * param_bytes + opt_bytes
                      + bucket * client_bytes)

    # --- sharded engine: same pairs, same weights, fused round step
    _p(f"agg_sharded bench: sharded rounds over "
       f"{int(np.prod(list(mesh.shape.values())))} devices")
    eng_s = ShardedBucketedAggregator(bucket, mesh)
    srv_s = ShardedFedOptServer(args_ns, params, eng_s)
    layout = eng_s.layout_for(params)
    g_s = eng_s.aggregate_round(pairs_for(0, clients), srv_s)  # warmup round
    jax.block_until_ready(g_s)
    warm_traces = eng_s.sharded_traces
    t0 = time.perf_counter()
    for r in range(1, rounds):
        g_s = eng_s.aggregate_round(pairs_for(r, clients), srv_s)
    jax.block_until_ready(g_s)
    shard_rate = k * (rounds - 1) / (time.perf_counter() - t0)
    if eng_s.sharded_traces != warm_traces or srv_s.round_traces != 1:
        raise BenchIntegrityError(
            f"sharded round step recompiled across rounds (accum traces "
            f"{warm_traces} -> {eng_s.sharded_traces}, round traces "
            f"{srv_s.round_traces}); refusing to publish")

    # parity: the final global params after IDENTICAL rounds must agree (the
    # flat-group contraction reorders the reduction, nothing else)
    host_u = jax.tree.map(np.asarray, g_u)
    host_s = srv_s.materialize_broadcast()
    max_rel = 0.0
    for a, b in zip(jax.tree.leaves(host_u), jax.tree.leaves(host_s)):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        # per-leaf max-abs error normalized by the LEAF scale: an
        # elementwise-relative metric divides by near-zero entries (adam
        # keeps many) and reports noise as divergence
        rel = float(np.max(np.abs(a - b))) / (float(np.max(np.abs(a))) + 1e-12)
        max_rel = max(max_rel, rel)
    tol = 5e-2 if client_dtype == jnp.bfloat16 else 1e-3
    if max_rel > tol:
        raise BenchIntegrityError(
            f"sharded-vs-unsharded parity failed: max rel err {max_rel:.3e} "
            f"> {tol:g}; refusing to publish")

    # per-device high-water, analytic: the booked accumulator + fedopt
    # params/opt-state shards + one in-flight bucket + the finalized view
    booked = dmesh.shard_bytes_by_device()
    sharded_per_dev = (max(booked.values())
                       + bucket * layout.shard_bytes(np.dtype(client_dtype))
                       + layout.shard_bytes())
    ratio = sharded_per_dev / unsharded_peak
    if ratio > 0.60:
        raise BenchIntegrityError(
            f"sharded per-device peak {sharded_per_dev / 1e6:.1f}MB is "
            f"{ratio:.0%} of the unsharded single-device peak "
            f"{unsharded_peak / 1e6:.1f}MB (> 60% acceptance bound); "
            "refusing to publish")
    measured = {}
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001 - CPU/tunnel devices expose none
            ms = None
        if ms and ms.get("peak_bytes_in_use"):
            measured[str(d)] = int(ms["peak_bytes_in_use"])
    hbm_source = "analytic+memory_stats" if measured else "analytic"

    # --- ingestion-overlap efficiency: host deltas exercise the per-shard
    # device_put stream; serial reference BLOCKS each transfer before its
    # accumulation dispatches, overlapped is the engine's own loop
    _p("agg_sharded bench: ingestion-overlap measurement")
    host_clients = [jax.tree.map(np.asarray, c) for c in clients]
    host_pairs = pairs_for(0, host_clients)
    jax.block_until_ready(eng_s.aggregate(host_pairs))  # warm finalize path
    t0 = time.perf_counter()
    jax.block_until_ready(eng_s.aggregate(host_pairs))
    dt_overlap = time.perf_counter() - t0
    buckets = []
    for start in range(0, k, bucket):
        chunk = host_pairs[start:start + bucket]
        trees = [t for _, t in chunk]
        w = np.asarray([wgt for wgt, _ in chunk], np.float32)
        if len(trees) < bucket:
            pad = bucket - len(trees)
            trees = trees + [trees[-1]] * pad
            w = np.concatenate([w, np.zeros((pad,), np.float32)])
        buckets.append((trees, w))
    t0 = time.perf_counter()
    acc = None
    for bk in buckets:
        cur = eng_s._ingest_bucket(bk, layout)
        jax.block_until_ready(cur[0])  # serialize: transfer lands first
        acc = eng_s._saccum_first(*cur) if acc is None else eng_s._saccum(acc, *cur)
        jax.block_until_ready(acc)
    jax.block_until_ready(eng_s._finalize_sharded_fn(layout)(acc))
    dt_serial = time.perf_counter() - t0
    overlap_eff = dt_serial / dt_overlap

    span_summary = {
        name: {"count": v["count"], "total_ms": round(v["total_ms"], 1),
               "max_ms": round(v["max_ms"], 2)}
        for name, v in tel.snapshot()["span_stats"].items()
        if name.startswith("agg.")
    }
    return {
        "agg_sharded_mesh": dmesh.mesh_topology(mesh),
        "agg_sharded_bucket_size": bucket,
        "agg_sharded_cohort": k,
        "agg_sharded_rounds": rounds,
        "agg_sharded_clients_per_sec": round(shard_rate, 1),
        "agg_unsharded_clients_per_sec": round(unshard_rate, 1),
        "agg_sharded_per_device_bytes": int(sharded_per_dev),
        "agg_unsharded_peak_bytes": int(unsharded_peak),
        "agg_sharded_hbm_ratio": round(ratio, 4),
        "hbm_source": hbm_source,
        "per_device_peak_measured": measured or None,
        "agg_sharded_overlap_efficiency": round(overlap_eff, 3),
        "agg_sharded_traces": eng_s.sharded_traces,
        "agg_round_traces": srv_s.round_traces,
        "agg_sharded_parity_max_rel_err": float(f"{max_rel:.3e}"),
        "agg_sharded_pytree": {
            "n_params": int(n_params),
            "client_dtype": str(np.dtype(client_dtype)),
            "geometry": geometry,
        },
        "agg_sharded_span_summary": span_summary,
        "device": getattr(dev, "device_kind", str(dev)),
    }


def _bench_async_rounds(publishes: int = 8, reps: int = 3):
    """Asynchronous buffered federation (ISSUE 9): rounds/hr INDEPENDENT of
    cohort size. The event-driven simulator
    (simulation/vmapped/async_driver.py) runs 1k/10k/100k clients with
    heterogeneous delays against a fresh AsyncAggBuffer; a "round" is a
    publish (every publish_k merges), so the server-side work per round is
    O(publish_k) no matter how many clients are in flight. rounds/hr divides
    publishes by the SERVER seconds (submit folds + publishes, perf_counter
    around exactly those calls) — delta generation is simulated client
    compute, massively parallel in a real fleet and overlapped with server
    work in the PiPar sense, so it does not belong in the denominator.

    Integrity guards (BenchIntegrityError, refusing to publish):
    - parity: staleness exponent 0 + publish_k == cohort == bucket must
      reproduce the synchronous engine.aggregate BIT-EXACTLY (same pairs,
      same order); the multi-bucket streaming path must agree at 1e-6.
    - flatness: min-of-reps rounds/hr at the largest cohort must be within
      FEDML_ASYNC_FLATNESS_TOL (default 1.1x) of the smallest cohort.
    - zero retraces: the engine's accumulate trace counters must not move
      after warmup (one steady-state fold program across ALL cohorts)."""
    import jax

    from fedml_tpu.core.aggregation.async_buffer import AsyncAggBuffer, StalenessPolicy
    from fedml_tpu.core.aggregation.bucketed import BucketedAggregator
    from fedml_tpu.simulation.vmapped.async_driver import (
        AsyncEventSim,
        DelayModel,
        make_synthetic_delta_fn,
    )

    dev = jax.devices()[0]
    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    cohorts = (100, 400, 1000) if tiny else (1000, 10000, 100000)
    bucket = 16
    publish_k = 2 * bucket  # > bucket: exercises the streaming fold path
    eng = BucketedAggregator(bucket)  # fresh engine: clean trace counters

    # model proxy: a ~100k-param MLP-shaped pytree — the fold cost scales
    # with bytes, the FLATNESS claim is about the cohort axis
    key = np.random.default_rng(5)
    template = {
        "dense1": {"kernel": np.asarray(key.standard_normal((128, 256)), np.float32),
                   "bias": np.zeros((256,), np.float32)},
        "dense2": {"kernel": np.asarray(key.standard_normal((256, 256)), np.float32),
                   "bias": np.zeros((256,), np.float32)},
        "head": {"kernel": np.asarray(key.standard_normal((256, 64)), np.float32),
                 "bias": np.zeros((64,), np.float32)},
    }
    template = jax.device_put(template)
    n_params = sum(x.size for x in jax.tree.leaves(template))
    gen = make_synthetic_delta_fn(seed=11)

    # --- parity guards (the acceptance anchor) -----------------------------
    def _unstack(stacked, n):
        return [jax.tree.map(lambda l, _k=k: l[_k], stacked) for k in range(n)]

    ids = np.arange(bucket, dtype=np.int32)
    trees = _unstack(gen(template, ids, 0), bucket)
    weights = (np.arange(bucket) + 1.0).astype(np.float64)
    buf = AsyncAggBuffer(publish_k=bucket, policy=StalenessPolicy(exponent=0.0),
                         engine=eng)
    for k in range(bucket):
        buf.submit(k, trees[k], float(weights[k]), 0)
    got = buf.publish()
    want = eng.aggregate([(float(weights[k]), trees[k]) for k in range(bucket)])
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            raise BenchIntegrityError(
                "async parity failed: exponent 0 + publish_k == cohort must "
                "be BIT-EXACT with synchronous engine.aggregate; refusing to "
                "publish")
    k3 = 3 * bucket
    trees3 = _unstack(gen(template, np.arange(k3, dtype=np.int32), 1), k3)
    w3 = (np.arange(k3) + 1.0).astype(np.float64)
    buf3 = AsyncAggBuffer(publish_k=k3, policy=StalenessPolicy(exponent=0.0),
                          engine=eng)
    for k in range(k3):
        buf3.submit(k, trees3[k], float(w3[k]), 0)
    got3 = buf3.publish()
    want3 = eng.aggregate([(float(w3[k]), trees3[k]) for k in range(k3)])
    # leaf-scale-normalized error (the agg_sharded metric): elementwise
    # relative error divides by near-cancelling entries and reports float
    # noise as divergence
    mb_err = 0.0
    for a, b in zip(jax.tree.leaves(got3), jax.tree.leaves(want3)):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        mb_err = max(mb_err, float(np.max(np.abs(a - b)))
                     / (float(np.max(np.abs(a))) + 1e-12))
    if mb_err > 1e-6:
        raise BenchIntegrityError(
            f"async multi-bucket parity failed: streaming scale-after-fold "
            f"drifted {mb_err:.3e} (> 1e-6 of leaf scale) from the "
            "synchronous path; refusing to publish")

    # --- cohort sweep ------------------------------------------------------
    def one_run(n_clients: int, seed: int):
        sim = AsyncEventSim(
            AsyncAggBuffer(publish_k=publish_k, engine=eng),
            gen, n_clients, initial_model=template,
            delay=DelayModel(n_clients, mean_delay=1.0, heterogeneity=0.5,
                             seed=seed),
            gen_batch=512)
        return sim.run(publishes)

    _p(f"async bench: warmup ({n_params / 1e3:.0f}k params, "
       f"publish_k={publish_k})")
    one_run(cohorts[0], seed=99)  # compiles fold + scale + finalize chain
    traces_before = int(eng.accum_traces)

    rounds_per_hr: dict = {}
    staleness_p50: dict = {}
    staleness_p99: dict = {}
    high_water: dict = {}
    rejected: dict = {}
    merge_us: dict = {}
    for n in cohorts:
        _p(f"async bench: cohort {n} x {reps} reps")
        best: dict | None = None
        for r in range(reps):
            stats = one_run(n, seed=1000 + r)
            if best is None or stats["server_seconds"] < best["server_seconds"]:
                best = stats
        rounds_per_hr[str(n)] = round(best["publishes"] / best["server_seconds"] * 3600.0, 1)
        staleness_p50[str(n)] = best["staleness_p50"]
        staleness_p99[str(n)] = best["staleness_p99"]
        high_water[str(n)] = best["buffer_high_water"]
        rejected[str(n)] = best["stale_rejected"]
        merge_us[str(n)] = round(best["server_seconds"] / max(best["merges"], 1) * 1e6, 1)

    if eng.accum_traces != traces_before:
        raise BenchIntegrityError(
            f"async fold retraced during the timed sweep ({traces_before} -> "
            f"{eng.accum_traces}); refusing to publish")

    # flatness: the claim itself. rounds/hr at the largest cohort within
    # tol x of the smallest (min-of-reps absorbs scheduler noise)
    tol = float(os.environ.get("FEDML_ASYNC_FLATNESS_TOL", "1.1"))
    small, large = rounds_per_hr[str(cohorts[0])], rounds_per_hr[str(cohorts[-1])]
    flatness = small / large if large else float("inf")
    if flatness > tol:
        raise BenchIntegrityError(
            f"async rounds/hr NOT cohort-independent: {cohorts[0]} clients -> "
            f"{small}/hr vs {cohorts[-1]} clients -> {large}/hr "
            f"({flatness:.2f}x > {tol}x); refusing to publish")

    # hierarchy rider: same workload through an 8-edge tree (fan-in per node
    # stays O(children); root version is the global round)
    _p("async bench: hierarchy rider (8 edges)")
    from fedml_tpu.core.distributed.hierarchy import HierarchyTree

    tree = HierarchyTree.build(8, publish_k=8, engine=eng, initial_model=template)
    hsim = AsyncEventSim(tree, gen, cohorts[0], initial_model=template,
                         delay=DelayModel(cohorts[0], seed=7), gen_batch=512)
    hstats = hsim.run(max(2, publishes // 2))

    return {
        "async_rounds_per_hr": rounds_per_hr,
        "async_flatness_ratio": round(flatness, 4),
        "async_staleness_p50": staleness_p50,
        "async_staleness_p99": staleness_p99,
        "async_buffer_high_water": high_water,
        "async_stale_rejected": rejected,
        "async_server_merge_us": merge_us,
        "async_publish_k": publish_k,
        "async_publishes_per_cohort": publishes,
        "async_cohorts": list(cohorts),
        "async_parity_bit_exact": True,
        "async_parity_multibucket_rel_err": float(f"{mb_err:.3e}"),
        "async_accum_traces": eng.accum_traces,
        "async_pytree_params": int(n_params),
        "async_hierarchy": {
            "edges": 8,
            "root_publishes": hstats["publishes"],
            "merges": hstats["merges"],
            "staleness_p99": hstats["staleness_p99"],
            "buffer_high_water": hstats["buffer_high_water"],
        },
        "device": getattr(dev, "device_kind", str(dev)),
    }


def _bench_fleet_scale():
    """Sketch-based fleet telemetry at million-client scale (ISSUE 19).

    1M synthetic clients (heavy-tail lognormal round times with planted
    40x stragglers, outlier-spiked delta norms, geometric staleness) are
    ingested edge-locally into a 3-tier HierarchyTree's mergeable sketches
    (DDSketch-style quantiles + count-min top-k offenders + HLL distinct
    clients), flushed edge->regional->root, and the ROOT's merged view is
    judged against numpy ground truth computed from the raw arrays. A
    second slice runs the vmapped event-clock driver through a real tree so
    the per-submit staleness sketch feed is exercised on the production
    path, not just the vectorized bulk one.

    Integrity guards (BenchIntegrityError, refusing to publish):
    - accuracy: root-view p50/p90/p99/p999 within 2% relative error of
      np.quantile on every family (the sketch promises <= 1% by
      construction; 2% leaves room for interpolation differences).
    - associativity: root view == flat single-sketch ingest — quantile
      buckets and HLL registers BIT-EXACT, count-min tables to float
      round-off — i.e. edge-merged == flat-merged.
    - memory: total resident sketch bytes across ALL nodes within 1.5x of
      a 100x-smaller reference run (O(sketch-bytes x nodes), NOT
      O(clients)), and < 64 bytes amortized per client.
    - overhead: on the driver slice (the production submit path, where
      sketch ingest rides real buffer folds) the self-accounted sketch
      ingest + merge time must stay < 1% of the slice wall. The bulk
      vectorized 1M-client feed is the harness computing ground truth —
      its absolute cost is reported (fleet_scale_ingest_seconds) but the
      overhead claim is about what telemetry adds to real server work."""
    import jax

    from fedml_tpu.core.aggregation.bucketed import BucketedAggregator
    from fedml_tpu.core.distributed.hierarchy import HierarchyTree
    from fedml_tpu.core.telemetry import sketches as fsk
    from fedml_tpu.simulation.vmapped.async_driver import (
        AsyncEventSim,
        DelayModel,
        make_synthetic_delta_fn,
    )

    t0 = time.monotonic()
    dev = jax.devices()[0]
    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    n_clients = 20_000 if tiny else 1_000_000
    n_edges = 16 if tiny else 64
    fanout = 4 if tiny else 8
    n_ref = n_clients // 100  # the memory-independence reference cohort
    n_planted = 12

    rng = np.random.default_rng(19)
    ranks = np.arange(n_clients, dtype=np.uint64)
    round_times = rng.lognormal(mean=1.0, sigma=0.6, size=n_clients)
    # stragglers are PERSISTENTLY slow, not slow once: each planted rank
    # recurs across many rounds at 40x — one lone slow observation is (by
    # design) below the count-min noise floor at 1M clients
    planted = rng.choice(n_clients, size=n_planted, replace=False)
    rep = max(8, n_clients // 2000)
    straggler_ranks = np.repeat(planted.astype(np.uint64), rep)
    straggler_times = 40.0 * rng.lognormal(1.0, 0.6, straggler_ranks.size)
    rt_ranks = np.concatenate([ranks, straggler_ranks])
    rt_vals = np.concatenate([round_times, straggler_times])
    delta_norms = np.abs(rng.normal(1.0, 0.25, size=n_clients)) + 1e-6
    out_mask = rng.random(n_clients) < 0.01
    delta_norms[out_mask] *= 25.0
    staleness = (rng.geometric(0.5, size=n_clients) - 1).astype(np.float64)

    def ingest(n: int, edges: int, reg_fanout: int):
        """Edge-local vectorized ingest + one flush; returns the tree, the
        root's merged view, and the flush wall seconds. ``n == n_clients``
        ingests the full arrays (straggler repeats included); the reference
        run takes the first ``n`` clients only."""
        tree = HierarchyTree.build(edges, regional_fanout=reg_fanout)
        rr = rt_ranks if n == n_clients else ranks[:n]
        rv = rt_vals if n == n_clients else round_times[:n]
        r = ranks[:n]
        rt_edge = (rr % np.uint64(edges)).astype(np.int64)
        edge_of = (r % np.uint64(edges)).astype(np.int64)
        for e_idx, edge in enumerate(tree.edges):
            rsel = rt_edge == e_idx
            sel = edge_of == e_idx
            sk = edge.fleet.sketches
            sk.observe_round_times(rr[rsel], rv[rsel])
            sk.observe_delta_norms(r[sel], delta_norms[:n][sel],
                                   n_outliers=int(out_mask[:n][sel].sum()))
            sk.observe_stalenesses(r[sel], staleness[:n][sel])
        tf = time.perf_counter()
        tree.flush_sketches()
        view = tree.root.fleet.sketch_view()
        return tree, view, time.perf_counter() - tf

    _p(f"fleet_scale: ingest {n_clients} clients across {n_edges} edges")
    tree, view, flush_s = ingest(n_clients, n_edges, fanout)

    # --- associativity: edge-merged == flat-merged -------------------------
    flat = fsk.FleetSketches()
    flat.observe_round_times(rt_ranks, rt_vals)
    flat.observe_delta_norms(ranks, delta_norms, n_outliers=int(out_mask.sum()))
    flat.observe_stalenesses(ranks, staleness)
    for fam in fsk.FLEET_FAMILIES:
        if view.quantiles[fam] != flat.quantiles[fam]:
            raise BenchIntegrityError(
                f"fleet_scale associativity failed: {fam} quantile buckets "
                "differ between edge-merged and flat ingest; refusing to "
                "publish")
    if not np.array_equal(view.clients.registers, flat.clients.registers):
        raise BenchIntegrityError(
            "fleet_scale associativity failed: HLL registers differ between "
            "edge-merged and flat ingest; refusing to publish")
    cms_drift = float(np.max(np.abs(view.offenders.table - flat.offenders.table))
                      / (np.max(np.abs(flat.offenders.table)) + 1e-12))
    if cms_drift > 1e-9:
        raise BenchIntegrityError(
            f"fleet_scale associativity failed: count-min tables drifted "
            f"{cms_drift:.3e} (> 1e-9 of table scale); refusing to publish")

    # wire roundtrip must preserve the merged view exactly
    rt_view = fsk.FleetSketches.from_wire(view.to_wire())
    if any(rt_view.quantiles[f] != view.quantiles[f] for f in fsk.FLEET_FAMILIES):
        raise BenchIntegrityError(
            "fleet_scale wire roundtrip changed quantile buckets; refusing "
            "to publish")

    # --- accuracy vs numpy ground truth ------------------------------------
    exact_arrays = {"round_time_s": rt_vals, "delta_norm": delta_norms,
                    "staleness": staleness}
    err_pct = 0.0
    quantile_rows: dict = {}
    for fam, arr in exact_arrays.items():
        row = {}
        for q in fsk.FLEET_QUANTILES:
            est = view.quantiles[fam].quantile(q)
            exact = float(np.quantile(arr, q))
            rel = abs(est - exact) / max(abs(exact), 1e-9)
            err_pct = max(err_pct, 100.0 * rel)
            row[str(q)] = round(est, 6)
        quantile_rows[fam] = row
    if err_pct > 2.0:
        raise BenchIntegrityError(
            f"fleet_scale quantile error {err_pct:.3f}% > 2% vs numpy exact; "
            "refusing to publish")

    # planted stragglers must surface in the root's top-k offender heap
    top_keys = {ki for ki, _ in view.offenders.topk()}
    recovered = sum(1 for p in planted if int(p) in top_keys)
    if recovered < n_planted - 2:
        raise BenchIntegrityError(
            f"fleet_scale top-k missed planted stragglers: {recovered}/"
            f"{n_planted} recovered; refusing to publish")

    hll_err_pct = 100.0 * abs(view.clients.estimate() - n_clients) / n_clients

    # --- memory: O(sketch-bytes x nodes), not O(clients) --------------------
    def resident_bytes(t: HierarchyTree) -> int:
        total = 0
        for node in [t.root, *t.regionals, *t.edges]:
            total += node.fleet.sketches.nbytes()
            total += sum(cs.nbytes() for cs in node.fleet._child_sketches.values())
        return total

    big_bytes = resident_bytes(tree)
    _p(f"fleet_scale: reference ingest {n_ref} clients")
    ref_tree, _, _ = ingest(n_ref, n_edges, fanout)
    ref_bytes = resident_bytes(ref_tree)
    mem_ratio = big_bytes / max(ref_bytes, 1)
    bytes_per_client = big_bytes / n_clients
    n_bundles = 0  # one sketch bundle per node + per forwarded child slot
    for node in [tree.root, *tree.regionals, *tree.edges]:
        n_bundles += 1 + len(node.fleet._child_sketches)
    if mem_ratio > 1.5:
        raise BenchIntegrityError(
            f"fleet_scale telemetry memory scaled with cohort: {big_bytes}B "
            f"at {n_clients} clients vs {ref_bytes}B at {n_ref} "
            f"({mem_ratio:.2f}x > 1.5x); refusing to publish")
    if big_bytes > n_bundles * 262_144:
        raise BenchIntegrityError(
            f"fleet_scale sketch bundles average {big_bytes // n_bundles}B "
            "(> 256KiB each): footprint is no longer topology-bounded; "
            "refusing to publish")
    if n_clients >= 500_000 and bytes_per_client > 64.0:
        raise BenchIntegrityError(
            f"fleet_scale telemetry costs {bytes_per_client:.1f}B/client at "
            "full scale (> 64B amortized); refusing to publish")

    # --- event-clock driver slice: the production submit path --------------
    _p("fleet_scale: event-clock driver slice")
    eng = BucketedAggregator(16)
    key = np.random.default_rng(23)
    # ~100k-param MLP proxy (the async_rounds pytree): hop + observe costs
    # are judged against folds of a realistically-sized model, not a toy
    template = jax.device_put({
        "dense1": {"kernel": np.asarray(key.standard_normal((128, 256)), np.float32),
                   "bias": np.zeros((256,), np.float32)},
        "dense2": {"kernel": np.asarray(key.standard_normal((256, 256)), np.float32),
                   "bias": np.zeros((256,), np.float32)},
        "head": {"kernel": np.asarray(key.standard_normal((256, 64)), np.float32),
                 "bias": np.zeros((64,), np.float32)}})
    gen = make_synthetic_delta_fn(seed=3)
    sim_tree = HierarchyTree.build(8 if tiny else 16, publish_k=8, engine=eng,
                                   initial_model=template)
    sim = AsyncEventSim(sim_tree, gen, n_clients, initial_model=template,
                        delay=DelayModel(n_clients, seed=7), gen_batch=512)
    sim.run(1)  # warmup: compiles the fold/publish chain off the clock
    sim_nodes = [sim_tree.root, *sim_tree.regionals, *sim_tree.edges]
    obs_before = sum(n.fleet.sketches.quantiles["staleness"].count
                     for n in sim_nodes)
    fwd_before = sum(n.forwards for n in sim_nodes)
    sim_t0 = time.perf_counter()
    sim_stats = sim.run(4 if tiny else 8)
    sim_tree.flush_sketches()
    sim_wall = time.perf_counter() - sim_t0
    n_obs = sum(n.fleet.sketches.quantiles["staleness"].count
                for n in sim_nodes) - obs_before
    # each forward (and each end-of-run flush) ships one sketch wire hop:
    # child view copy+serialize at the sender, parse at the receiver
    n_hops = (sum(n.forwards for n in sim_nodes) - fwd_before
              + len(sim_tree.regionals) + len(sim_tree.edges))
    sim_view = sim_tree.root.fleet.sketch_view()
    if sim_view.quantiles["staleness"].count == 0:
        raise BenchIntegrityError(
            "fleet_scale driver slice fed ZERO staleness observations into "
            "the sketches; the submit path is not wired; refusing to publish")

    # --- overhead: sketch time riding the production submit path ------------
    # Attribution is CALIBRATED, not self-timed in-loop: perf_counter windows
    # inside the sim absorb GIL waits on jax's async fold threads and bill
    # telemetry for the server's own compute. Calibrate each per-event cost
    # standalone, then charge events x unit cost against the slice wall.
    cal_scratch = fsk.FleetSketches()
    cal_n = 20_000
    cal_t0 = time.perf_counter()
    for i in range(cal_n):
        cal_scratch.observe_staleness(i & 1023, float(i & 7))
    per_obs_s = (time.perf_counter() - cal_t0) / cal_n
    cal_edge = sim_tree.edges[0].fleet
    cal_t0 = time.perf_counter()
    for _ in range(64):
        fsk.FleetSketches.from_wire(cal_edge.wire_view())
    per_hop_s = (time.perf_counter() - cal_t0) / 64
    ingest_s = sum(e.fleet.sketches.observe_ns for e in tree.edges) / 1e9
    merge_s = flush_s + view.merge_ns / 1e9
    sim_sketch_s = n_obs * per_obs_s + n_hops * per_hop_s
    overhead_pct = 100.0 * sim_sketch_s / max(sim_wall, 1e-9)
    if overhead_pct > 1.0:
        raise BenchIntegrityError(
            f"fleet_scale sketch ingest+merge took {overhead_pct:.2f}% of "
            f"the driver-slice wall (> 1%: {n_obs} observes x "
            f"{per_obs_s * 1e6:.1f}us + {n_hops} hops x "
            f"{per_hop_s * 1e6:.0f}us vs {sim_wall:.2f}s); refusing to "
            "publish")
    stage_wall = time.monotonic() - t0

    return {
        "fleet_scale_clients": n_clients,
        "fleet_scale_nodes": 1 + len(tree.regionals) + len(tree.edges),
        "fleet_scale_quantile_err_pct": round(err_pct, 4),
        "fleet_telemetry_bytes_per_client": round(bytes_per_client, 3),
        "fleet_scale_total_sketch_bytes": int(big_bytes),
        "fleet_scale_mem_ratio_vs_ref": round(mem_ratio, 4),
        "fleet_scale_ingest_overhead_pct": round(overhead_pct, 4),
        "fleet_scale_ingest_seconds": round(ingest_s + merge_s, 4),
        "fleet_scale_driver_slice_seconds": round(sim_wall, 4),
        "fleet_scale_stage_wall_seconds": round(stage_wall, 2),
        "fleet_scale_edge_eq_flat": True,
        "fleet_scale_cms_table_drift": float(f"{cms_drift:.3e}"),
        "fleet_scale_offenders_recovered": f"{recovered}/{n_planted}",
        "fleet_scale_hll_err_pct": round(hll_err_pct, 3),
        "fleet_scale_straggler_ratio": round(view.straggler_ratio(), 5),
        "fleet_scale_outlier_rate": round(view.outlier_rate(), 5),
        "fleet_scale_quantiles": quantile_rows,
        "fleet_scale_sim": {
            "publishes": sim_stats["publishes"],
            "merges": sim_stats["merges"],
            "staleness_observations": int(sim_view.quantiles["staleness"].count),
        },
        "device": getattr(dev, "device_kind", str(dev)),
    }


def _bench_wan_profile():
    """Per-link WAN observability (ISSUE 12): a heterogeneous-throttle
    in-memory fleet must be MEASURABLE by the netlink estimators. One
    server-side LinkProber probes N echo-loop clients through the real
    InMemoryBroker with per-rank ``chaos_link_throttle`` profiles injected;
    the probe traffic is real ``Message`` objects passing through the same
    ``record_send``/``record_recv`` hooks as production comm, so the passive
    accounting, the active RTT/bandwidth estimators, and the cost model all
    run exactly the code the cross-silo managers run.

    Integrity guards (BenchIntegrityError, refusing to publish):
    - convergence: every throttled pair's bandwidth estimate must land
      within FEDML_WAN_BW_TOL (default 20%) of its injected bytes/sec, with
      >= 3 retained samples — an estimator that cannot recover a KNOWN
      synthetic profile has no business steering deadlines;
    - overhead: total ``link.probe`` span time must stay under
      FEDML_WAN_OVERHEAD_TOL_PCT (default 1%) of the probing window wall
      time — active probing is only admissible if it is ~free;
    - liveness: >= 80% of sent probes must be answered (a timeout
      misconfigured against the injected RTT would silently turn the bw
      series into loss noise)."""
    import queue
    import threading

    from fedml_tpu.core import telemetry as tel
    from fedml_tpu.core.distributed.communication.inmemory.broker import InMemoryBroker
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.core.distributed.link_probe import LinkProber
    from fedml_tpu.core.telemetry import netlink
    from fedml_tpu.cross_silo.message_define import MyMessage

    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    # injected per-rank WAN profile (bytes/sec). Payload sized so the
    # transfer term dominates timer jitter (~ms) even on the fastest link.
    if tiny:
        profile = {1: 2 * (1 << 20), 2: 512 * 1024}
        payload_bytes, interval_s, ticks = 65536, 0.2, 8
    else:
        profile = {1: 4 * (1 << 20), 2: 1 << 20, 3: 256 * 1024}
        payload_bytes, interval_s, ticks = 131072, 0.25, 12
    base_delay_s = 0.02  # propagation floor: the zero-payload probe's RTT/2
    run_id = "bench_wan_profile"
    backend = "INMEMORY"

    InMemoryBroker.reset(run_id)
    broker = InMemoryBroker.get(run_id)
    for rank, bps in profile.items():
        broker.set_throttle(rank, bps, base_delay_s)

    netlink.reset()
    registry = netlink.get_registry()
    t = tel.get_telemetry()
    tel_was_enabled = t.enabled
    t.set_enabled(True)
    t.reset()

    stop_evt = threading.Event()

    def _client_loop(rank: int) -> None:
        # stateless probe echoer: exactly what fedml_client_master_manager
        # does, minus the trainer
        q = broker.queue_for(rank)
        while not stop_evt.is_set():
            try:
                msg = q.get(timeout=0.1)
            except queue.Empty:
                continue
            registry.record_recv(msg, backend=backend)
            if msg.get_type() != MyMessage.MSG_TYPE_LINK_PROBE:
                continue
            echo = Message(MyMessage.MSG_TYPE_LINK_PROBE_ECHO, rank, 0)
            for key in (MyMessage.MSG_ARG_KEY_PROBE_SEQ,
                        MyMessage.MSG_ARG_KEY_PROBE_T_SEND_NS,
                        MyMessage.MSG_ARG_KEY_PROBE_NBYTES,
                        MyMessage.MSG_ARG_KEY_PROBE_PAD):
                val = msg.get(key)
                if val is not None:
                    echo.add_params(key, val)
            registry.record_send(echo, backend=backend)
            broker.publish(0, echo)

    def _send_probe(peer: int, seq: int, t_send_ns: int, nbytes: int) -> None:
        m = Message(MyMessage.MSG_TYPE_LINK_PROBE, 0, peer)
        m.add_params(MyMessage.MSG_ARG_KEY_PROBE_SEQ, seq)
        m.add_params(MyMessage.MSG_ARG_KEY_PROBE_T_SEND_NS, t_send_ns)
        m.add_params(MyMessage.MSG_ARG_KEY_PROBE_NBYTES, nbytes)
        if nbytes > 0:
            m.add_params(MyMessage.MSG_ARG_KEY_PROBE_PAD,
                         np.zeros(int(nbytes), dtype=np.uint8))
        registry.record_send(m, backend=backend)
        broker.publish(peer, m)

    prober = LinkProber(
        local_rank=0, send_probe=_send_probe,
        peers=lambda: list(profile), interval_s=interval_s,
        payload_bytes=payload_bytes,
        # timeout must clear the SLOWEST injected RTT: 2*(base + payload/bps)
        timeout_intervals=(2.0 * (base_delay_s + payload_bytes / min(profile.values()))
                          / interval_s) + 4.0,
        registry=registry, backend=backend)

    def _server_loop() -> None:
        q = broker.queue_for(0)
        while not stop_evt.is_set():
            try:
                msg = q.get(timeout=0.1)
            except queue.Empty:
                continue
            registry.record_recv(msg, backend=backend)
            if msg.get_type() == MyMessage.MSG_TYPE_LINK_PROBE_ECHO:
                prober.observe_echo(
                    msg.get_sender_id(),
                    msg.get(MyMessage.MSG_ARG_KEY_PROBE_SEQ),
                    msg.get(MyMessage.MSG_ARG_KEY_PROBE_T_SEND_NS))

    threads = [threading.Thread(target=_server_loop, name="wan-server", daemon=True)]
    threads += [threading.Thread(target=_client_loop, args=(r,),
                                 name=f"wan-client-{r}", daemon=True)
                for r in profile]
    slowest_rtt = 2.0 * (base_delay_s + payload_bytes / min(profile.values()))
    _p(f"wan_profile: {len(profile)} clients, payload {payload_bytes}B, "
       f"{ticks} ticks @ {interval_s}s (slowest injected rtt {slowest_rtt:.2f}s)")

    wall_t0 = time.perf_counter()
    for th in threads:
        th.start()
    try:
        # deterministic cadence (prober.tick, not the thread): exactly
        # `ticks` probe pairs per peer, no partial-tail ambiguity
        for _ in range(ticks):
            prober.tick()
            time.sleep(interval_s)  # fedlint: disable=bare-sleep probe cadence, not a retry
        # drain: the slowest pair's last padded echo is still in flight
        time.sleep(slowest_rtt + 0.5)  # fedlint: disable=bare-sleep waiting out the injected link delay, not a retry
    finally:
        wall_s = time.perf_counter() - wall_t0
        stop_evt.set()
        for th in threads:
            th.join(timeout=2.0)
        for rank in profile:
            broker.clear_throttle(rank)
        InMemoryBroker.reset(run_id)

    # --- convergence guard -------------------------------------------------
    tol = float(os.environ.get("FEDML_WAN_BW_TOL", "0.20"))
    cost = registry.cost_model()
    per_link: dict = {}
    worst_err_pct = 0.0
    for rank, injected in sorted(profile.items()):
        stats = registry.pair((0, rank), create=False)
        measured = None if stats is None else stats.bw.value
        count = 0 if stats is None else stats.bw.count
        if measured is None or count < 3:
            raise BenchIntegrityError(
                f"wan_profile: pair 0->{rank} has no converged bandwidth "
                f"estimate ({count} retained samples) after {ticks} probe "
                "ticks; refusing to publish")
        err = abs(measured - injected) / injected
        worst_err_pct = max(worst_err_pct, 100.0 * err)
        if err > tol:
            raise BenchIntegrityError(
                f"wan_profile: pair 0->{rank} estimated "
                f"{measured / 1e6:.3f} MB/s vs injected {injected / 1e6:.3f} "
                f"MB/s ({100 * err:.1f}% > {100 * tol:.0f}%); refusing to publish")
        pred = cost.predict_transfer_s(0, rank, 1 << 20)
        per_link[str(rank)] = {
            "injected_bytes_per_sec": injected,
            "measured_bytes_per_sec": round(measured, 1),
            "bw_error_pct": round(100.0 * err, 2),
            "rtt_ms": (None if stats.rtt.value is None
                       else round(stats.rtt.value * 1e3, 2)),
            "loss_ratio": round(stats.loss_ratio(), 4),
            "predicted_mib_s": (None if pred.seconds is None
                                else round(pred.seconds, 4)),
            "confidence": round(pred.confidence, 3),
        }

    # --- liveness guard ----------------------------------------------------
    sent = sum(s.probes_sent for s in registry.pairs().values())
    answered = sum(s.probes_answered for s in registry.pairs().values())
    if sent == 0 or answered < 0.8 * sent:
        raise BenchIntegrityError(
            f"wan_profile: only {answered}/{sent} probes answered (< 80%) — "
            "probe timeout is misconfigured against the injected RTT; "
            "refusing to publish")

    # --- overhead guard ----------------------------------------------------
    probe_stats = t.snapshot()["span_stats"].get("link.probe") or {}
    probe_ms = float(probe_stats.get("total_ms", 0.0))
    overhead_pct = 100.0 * probe_ms / (wall_s * 1e3)
    overhead_tol = float(os.environ.get("FEDML_WAN_OVERHEAD_TOL_PCT", "1.0"))
    if overhead_pct >= overhead_tol:
        raise BenchIntegrityError(
            f"wan_profile: probing consumed {overhead_pct:.3f}% of the "
            f"window wall time (>= {overhead_tol}%); active probing must be "
            "~free; refusing to publish")

    if not tel_was_enabled:
        t.set_enabled(False)
    netlink.reset()
    return {
        "wan_profile": per_link,
        "link_bw_error_pct": round(worst_err_pct, 2),
        "probe_overhead_pct": round(overhead_pct, 4),
        "wan_probe_ticks": ticks,
        "wan_probes_sent": sent,
        "wan_probes_answered": answered,
        "wan_probe_payload_bytes": payload_bytes,
        "wan_window_s": round(wall_s, 2),
    }


def _bench_pipeline_overlap():
    """Pipelined round execution (ISSUE 15): the stage executor must HIDE
    uplink time under compute on a real throttled link. Per client the
    round payload is split into the micro-batch count the link-cost planner
    picks (``plan_micro_batches`` over a netlink model primed with measured
    probes of the injected throttle), then train/compress/uplink run once
    serially and once through ``PipelinedExecutor`` — same work, same
    broker, same split-learning ``Message`` vocabulary for the uplink/ack
    round trip, so the only variable is the overlap.

    Integrity guards (BenchIntegrityError, refusing to publish):
    - overlap: mean measured ``overlap_frac`` across clients must be >=
      FEDML_PIPE_OVERLAP_MIN (default 0.5) — a pipeline that cannot hide
      at least half the hideable time is not a pipeline;
    - speedup: pipelined wall must strictly beat the serial wall on the
      identical workload;
    - planning: the micro-batch plan must come out of the cost model with
      reason "balanced" — a cold or misprimed model silently falling back
      to default chunks would make the overlap number meaningless."""
    import queue
    import threading

    from fedml_tpu.core.distributed.communication.inmemory.broker import InMemoryBroker
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.core.pipeline import PipelinedExecutor, StageSpec, plan_micro_batches
    from fedml_tpu.core.telemetry import netlink
    from fedml_tpu.cross_silo.message_define import MyMessage

    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    clients = [1, 2] if tiny else [1, 2, 3]
    payload_bytes = (128 if tiny else 256) * 1024
    bw_bps = float(1 << 20)  # 1 MiB/s injected uplink
    base_delay_s = 0.005
    # compute sized to 2x the bulk transfer: squarely "balanced" territory
    # for the planner, and enough compute to hide every chunk under
    train_total_s = 2.0 * payload_bytes / bw_bps
    run_id = "bench_pipeline_overlap"

    InMemoryBroker.reset(run_id)
    broker = InMemoryBroker.get(run_id)
    broker.set_throttle(0, bw_bps, base_delay_s)

    # --- prime the link-cost model with probes of the injected link -------
    netlink.reset()
    registry = netlink.get_registry()
    probe_nbytes = int(bw_bps * 2.0 * base_delay_s)
    for _ in range(5):
        registry.observe_probe(1, 0, 2.0 * base_delay_s, 0)
        registry.observe_probe(
            1, 0, 2.0 * base_delay_s + 2.0 * probe_nbytes / bw_bps, probe_nbytes)
    plan = plan_micro_batches(payload_bytes, train_total_s, src=1, dst=0,
                              min_chunks=2, max_chunks=8)
    if plan.reason != "balanced":
        broker.clear_throttle(0)
        InMemoryBroker.reset(run_id)
        netlink.reset()
        raise BenchIntegrityError(
            f"pipeline_overlap: planner fell back ({plan.reason!r}, "
            f"confidence {plan.confidence:.2f}) instead of sizing from the "
            "primed cost model; refusing to publish")
    m = plan.n_micro_batches
    chunk = payload_bytes // m
    per_mb_train_s = train_total_s / m

    # calibrate a real-compute train stage (matmul reps) to per_mb_train_s
    x = np.random.RandomState(0).rand(96, 96).astype(np.float32)
    t0 = time.perf_counter()
    for _ in range(32):
        x @ x
    t_once = (time.perf_counter() - t0) / 32.0
    reps = max(1, int(round(per_mb_train_s / t_once)))
    rng = np.random.RandomState(1)
    payloads = {r: rng.randint(0, 256, payload_bytes, dtype=np.uint8)
                for r in clients}

    stop_evt = threading.Event()

    def _server_loop() -> None:
        # ack every streamed activation chunk with a (tiny) grad message —
        # the same C2S_SPLIT_ACT / S2C_SPLIT_GRAD types the split front uses
        q = broker.queue_for(0)
        while not stop_evt.is_set():
            try:
                msg = q.get(timeout=0.1)
            except queue.Empty:
                continue
            if msg.get_type() != MyMessage.MSG_TYPE_C2S_SPLIT_ACT:
                continue
            ack = Message(MyMessage.MSG_TYPE_S2C_SPLIT_GRAD, 0,
                          msg.get_sender_id())
            ack.add_params(MyMessage.MSG_ARG_KEY_SPLIT_MB_IDX,
                           msg.get(MyMessage.MSG_ARG_KEY_SPLIT_MB_IDX))
            broker.publish(msg.get_sender_id(), ack)

    def _stages_for(rank: int):
        data = payloads[rank]
        ackq = broker.queue_for(rank)

        def train(i: int):
            for _ in range(reps):
                x @ x
            return i, data[i * chunk:(i + 1) * chunk]

        def compress(item):
            i, arr = item
            return i, arr.tobytes()

        def uplink(item):
            i, blob = item
            msg = Message(MyMessage.MSG_TYPE_C2S_SPLIT_ACT, rank, 0)
            msg.add_params(MyMessage.MSG_ARG_KEY_SPLIT_MB_IDX, i)
            msg.add_params(MyMessage.MSG_ARG_KEY_SPLIT_ACTS,
                           np.frombuffer(blob, dtype=np.uint8))
            broker.publish(0, msg)
            ackq.get(timeout=30.0)  # block for the transfer + grad ack
            return i

        return train, compress, uplink

    reports: dict = {}

    def _client_pipelined(rank: int) -> None:
        train, compress, uplink = _stages_for(rank)
        ex = PipelinedExecutor([
            StageSpec("train", train, maxsize=1),
            StageSpec("compress", compress, maxsize=2),
            StageSpec("uplink", uplink, maxsize=2),
        ], name=f"bench-pipe-{rank}")
        reports[rank] = ex.run(range(m))

    def _client_serial(rank: int) -> None:
        train, compress, uplink = _stages_for(rank)
        for i in range(m):
            uplink(compress(train(i)))

    def _fleet(target) -> float:
        threads = [threading.Thread(target=target, args=(r,),
                                    name=f"pipe-client-{r}", daemon=True)
                   for r in clients]
        t_start = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120.0)
        return time.perf_counter() - t_start

    _p(f"pipeline_overlap: {len(clients)} clients, {payload_bytes}B payload "
       f"-> m={m} x {chunk}B chunks ({plan.reason}), train {reps} matmul "
       f"reps/mb (~{per_mb_train_s * 1e3:.0f}ms)")

    server = threading.Thread(target=_server_loop, name="pipe-server",
                              daemon=True)
    server.start()
    try:
        _client_serial(clients[0])  # warmup: numpy + broker timers hot
        serial_wall_s = _fleet(_client_serial)
        pipe_wall_s = _fleet(_client_pipelined)
    finally:
        stop_evt.set()
        server.join(timeout=2.0)
        broker.clear_throttle(0)
        InMemoryBroker.reset(run_id)
        netlink.reset()

    overlaps = [reports[r].overlap_frac for r in clients]
    overlap_mean = sum(overlaps) / len(overlaps)
    speedup = serial_wall_s / pipe_wall_s if pipe_wall_s > 0 else 0.0

    overlap_min_req = float(os.environ.get("FEDML_PIPE_OVERLAP_MIN", "0.5"))
    if overlap_mean < overlap_min_req:
        raise BenchIntegrityError(
            f"pipeline_overlap: mean overlap_frac {overlap_mean:.3f} < "
            f"{overlap_min_req} (per-client {[round(o, 3) for o in overlaps]}); "
            "the pipeline is not hiding uplink under compute; refusing to "
            "publish")
    if speedup <= 1.0:
        raise BenchIntegrityError(
            f"pipeline_overlap: pipelined wall {pipe_wall_s:.3f}s did not "
            f"beat serial {serial_wall_s:.3f}s (speedup {speedup:.3f}); "
            "refusing to publish")

    bottlenecks = sorted({reports[r].bottleneck for r in clients})
    return {
        "pipeline_overlap_frac": round(overlap_mean, 4),
        "pipeline_overlap_frac_min": round(min(overlaps), 4),
        "pipeline_speedup": round(speedup, 3),
        "pipeline_serial_wall_s": round(serial_wall_s, 3),
        "pipeline_wall_s": round(pipe_wall_s, 3),
        "pipeline_micro_batches": m,
        "pipeline_chunk_nbytes": chunk,
        "pipeline_plan_reason": plan.reason,
        "pipeline_clients": len(clients),
        "pipeline_bottleneck": ",".join(bottlenecks),
    }


def _bench_slo_overhead():
    """SLO evaluator overhead (ISSUE 14): the tsdb ingest hook rides EVERY
    telemetry counter/histogram emission and the burn-rate evaluator ticks
    every round — observability that slows the round loop it watches is a
    bug. Drive a simulated round loop (real numpy work per round, the same
    engine.rounds/engine.round_seconds emissions RoundEngine books, one
    maybe_tick per round) through a real activated engine with a
    deliberately-breaching canary SLO riding args.slo_spec, then bill the
    evaluator's self-accounted time (tsdb ingest_ms + engine tick_ms)
    against the loop's wall time.

    Integrity guards (BenchIntegrityError, refusing to publish):
    - overhead: ingest + tick must stay under FEDML_SLO_OVERHEAD_TOL_PCT
      (default 1%) of the loop wall time;
    - liveness: the canary alert must FIRE during the loop (an evaluator
      that never evaluated has a meaningless overhead figure), ticks and
      ingested samples must both be nonzero."""
    import json as _json
    import tempfile

    import numpy as np

    from fedml_tpu.core import telemetry as tel
    from fedml_tpu.core.telemetry import slo

    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    # per-round work must be ROUND-SHAPED (ms-scale): the guard is a ratio,
    # and against a microsecond-scale loop even a free evaluator looks
    # expensive — no real front books rounds faster than milliseconds
    rounds = 240 if tiny else 600
    work_elems = 384

    # canary: engine.round_seconds "last" can never meet a 1e-9s target, so
    # the alert must walk ok->pending->firing while the loop runs — proving
    # the spec-file override path AND the evaluator end to end
    spec_doc = {"slos": [{"name": "bench_slo_canary",
                          "series": "engine.round_seconds",
                          "signal": "last", "comparator": "<=",
                          "target": 1e-9, "fast_window_s": 60,
                          "slow_window_s": 60,
                          "firing_for_ticks": 2, "clear_for_ticks": 2}]}
    spec_file = tempfile.NamedTemporaryFile(
        "w", suffix="_slo_spec.json", delete=False)
    _json.dump(spec_doc, spec_file)
    spec_file.close()

    class _Args:
        slo_spec = spec_file.name

    t = tel.get_telemetry()
    tel_was_enabled = t.enabled
    t.set_enabled(True)
    t.reset()
    engine = slo.activate(_Args(), front="engine")
    if engine is None:
        return {"skipped": "slo_disabled"}
    try:
        rng = np.random.default_rng(0)
        a = rng.standard_normal((work_elems, work_elems))
        b = rng.standard_normal((work_elems, work_elems))
        t0 = time.perf_counter()
        done = 0
        # at least `rounds` rounds AND >= 1.2s of wall: maybe_tick's 0.25s
        # production spacing needs multiple intervals for the canary to walk
        # ok -> pending -> firing (firing_for_ticks=2)
        while done < rounds or time.perf_counter() - t0 < 1.2:
            r0 = time.perf_counter()
            a = a @ b / float(work_elems)          # the "round" itself
            t.counter("engine.rounds").add(1)
            t.histogram("engine.round_seconds").observe(
                time.perf_counter() - r0)
            engine.maybe_tick()   # production spacing (0.25s floor)
            done += 1
            if done >= rounds * 200:               # pathological-fast guard
                break
        wall_s = time.perf_counter() - t0
        rounds = done
        if not np.isfinite(a).all():               # keep the matmul live
            raise BenchIntegrityError("slo_overhead: workload diverged")

        st = engine.statusz()
        store_st = engine.store.statusz()
        ticks = int(st["tick_count"])
        alerts_fired = int(st["alerts_fired"])
        overhead_ms = float(st["tick_ms"]) + float(store_st["ingest_ms"])
        overhead_pct = 100.0 * (overhead_ms / 1e3) / wall_s
        canary = st["slos"].get("bench_slo_canary") or {}
    finally:
        slo.deactivate(engine)
        if not tel_was_enabled:
            t.set_enabled(False)
        os.unlink(spec_file.name)

    _p(f"slo_overhead: {rounds} rounds in {wall_s:.2f}s, {ticks} ticks, "
       f"ingest+tick {overhead_ms:.2f}ms ({overhead_pct:.4f}% of wall), "
       f"canary state {canary.get('state')}, alerts_fired {alerts_fired}")

    if ticks == 0 or int(store_st["samples_total"]) == 0:
        raise BenchIntegrityError(
            f"slo_overhead: evaluator never ran (ticks {ticks}, samples "
            f"{store_st['samples_total']}) — overhead figure is meaningless; "
            "refusing to publish")
    if alerts_fired < 1 or canary.get("state") != slo.STATE_FIRING:
        raise BenchIntegrityError(
            f"slo_overhead: canary SLO never fired (state "
            f"{canary.get('state')!r}, alerts_fired {alerts_fired}) — the "
            "evaluator is not evaluating; refusing to publish")
    tol_pct = float(os.environ.get("FEDML_SLO_OVERHEAD_TOL_PCT", "1.0"))
    if overhead_pct >= tol_pct:
        raise BenchIntegrityError(
            f"slo_overhead: evaluator consumed {overhead_pct:.4f}% of the "
            f"round-loop wall time (>= {tol_pct}%); always-on observability "
            "must be ~free; refusing to publish")

    return {
        "slo_overhead_pct": round(overhead_pct, 4),
        "slo_ticks": ticks,
        "slo_ingest_ms": round(float(store_st["ingest_ms"]), 3),
        "slo_tick_ms": round(float(st["tick_ms"]), 3),
        "slo_samples": int(store_st["samples_total"]),
        "alerts_fired": alerts_fired,
        "slo_rounds": rounds,
        "slo_window_s": round(wall_s, 2),
    }


def _bench_modelwatch_overhead():
    """Modelwatch fold-boundary stats overhead (ISSUE 18): per-client delta
    statistics (norms, NaN/Inf counts, cosine drift) fused into the bucketed
    fold plus the once-per-round publish-time ``finish``. Observability that
    slows the round loop it watches is a bug — but the guard is a ratio, and
    a fold-only denominator would be dishonest the other way: no real front
    folds without having trained first (local training dominates every round
    by orders of magnitude). So, like slo_overhead, this drives a
    round-SHAPED loop — calibrated numpy work standing in for local
    training, then the bucketed fold + publish — once plain and once
    watched, and bills the difference in median round walls.

    Integrity guards (BenchIntegrityError, refusing to publish):
    - overhead: watched-vs-plain round wall delta must stay under
      FEDML_MODELWATCH_OVERHEAD_TOL_PCT (default 1%);
    - zero added recompiles: the fused watch variant and the stats programs
      must be fully traced during warmup — any trace-counter growth inside
      the timed loops fails the stage;
    - parity: the watched fold must be bit-exact vs the plain fold on the
      same cohort (stats must not change the math);
    - detection: a NaN client and a 50x-scaled client injected after the
      timed window must both be caught by the quarantine screen (an
      overhead figure for a watcher that watches nothing is meaningless)."""
    import numpy as np

    import jax

    from fedml_tpu.core import telemetry as tel
    from fedml_tpu.core.aggregation.bucketed import BucketedAggregator
    from fedml_tpu.core.telemetry import modelwatch
    from fedml_tpu.core.telemetry.jax_hooks import compile_count

    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    dim = 128 if tiny else 512
    clients = 8 if tiny else 16
    rounds = 8 if tiny else 16
    work_ratio = 120.0 if tiny else 200.0  # train:fold wall ratio (see above)

    t = tel.get_telemetry()
    tel_was_enabled = t.enabled
    t.set_enabled(True)
    try:
        rng = np.random.default_rng(0)

        def _tree(scale=1.0):
            return {"w": (rng.standard_normal((dim, dim)) * scale).astype(np.float32),
                    "b": (rng.standard_normal((dim,)) * scale).astype(np.float32)}

        # device-resident like a real server front: the global params never
        # live host-side between rounds
        ref = jax.tree.map(jax.numpy.asarray, _tree())
        cohort = [(1.0, _tree()) for _ in range(clients)]
        eng = BucketedAggregator(bucket_size=8)

        def _fold_plain():
            out = eng.aggregate(cohort)
            jax.block_until_ready(jax.tree.leaves(out))
            return out

        def _fold_watched(prev_update):
            sess = modelwatch.WatchSession(ref, prev_update=prev_update)
            out = eng.aggregate(cohort, watch=sess)
            stats = sess.finish(out)  # the one publish-time host fetch
            return out, stats

        # warmup compiles BOTH variants (+ the stats programs) and proves
        # the fused fold is bit-exact vs the plain one on the same cohort
        plain_out = _fold_plain()
        watched_out, stats = _fold_watched(None)
        prev_update = stats.update_tree
        for x, y in zip(jax.tree.leaves(plain_out), jax.tree.leaves(watched_out)):
            if not np.array_equal(np.asarray(x), np.asarray(y)):
                raise BenchIntegrityError(
                    "modelwatch_overhead: watched fold diverged from the "
                    "plain fold bit pattern; stats must not change the math")
        watched_out, stats = _fold_watched(prev_update)  # steady-state trace
        prev_update = stats.update_tree

        traces0 = (eng.accum_traces, eng.watch_traces,
                   compile_count("agg_accum"), compile_count("modelwatch"))

        # calibrate round-shaped work off the plain fold wall
        fold_samples = []
        for _ in range(5):
            f0 = time.perf_counter()
            _fold_plain()
            fold_samples.append(time.perf_counter() - f0)
        fold_s = max(float(np.median(fold_samples)), 1e-5)
        # the work unit is round-shaped (ms-scale) regardless of model size:
        # against a microsecond round even free stats look expensive, and
        # the per-round floor keeps the fixed dispatch cost of the watch
        # session honest at tiny model sizes too
        work_elems = 512
        a = rng.standard_normal((work_elems, work_elems))
        b = rng.standard_normal((work_elems, work_elems))
        w0 = time.perf_counter()
        a = a @ b / float(work_elems)
        unit_s = max(time.perf_counter() - w0, 1e-7)
        round_s = max(work_ratio * fold_s, 1.5)
        work_reps = max(1, min(4000, int(round_s / unit_s)))

        # interleave plain/watched rounds so machine drift hits both arms of
        # each pair equally; the guard compares paired-difference medians
        plain_walls, watched_walls = [], []
        for _ in range(rounds):
            r0 = time.perf_counter()
            for _ in range(work_reps):       # the "local training" itself
                a = a @ b / float(work_elems)
            _fold_plain()
            t1 = time.perf_counter()
            for _ in range(work_reps):
                a = a @ b / float(work_elems)
            _, stats = _fold_watched(prev_update)
            prev_update = stats.update_tree
            t2 = time.perf_counter()
            plain_walls.append(t1 - r0)
            watched_walls.append(t2 - t1)
        if not np.isfinite(a).all():           # keep the matmul live
            raise BenchIntegrityError("modelwatch_overhead: workload diverged")

        traces1 = (eng.accum_traces, eng.watch_traces,
                   compile_count("agg_accum"), compile_count("modelwatch"))
        med_plain = float(np.median(plain_walls))
        med_watched = float(np.median(watched_walls))
        delta_s = float(np.median(np.asarray(watched_walls) -
                                  np.asarray(plain_walls)))
        overhead_pct = 100.0 * delta_s / med_plain

        # detection liveness: the quarantine screen must catch an injected
        # NaN client AND a 50x-scaled client on a fresh cohort
        poisoned = list(cohort) + [(1.0, _tree(scale=50.0))]
        nan_tree = _tree()
        nan_tree["w"].flat[0] = np.nan
        poisoned.append((1.0, nan_tree))
        sess = modelwatch.WatchSession(ref)
        kept = modelwatch.screen_cohort(sess, poisoned,
                                        list(range(len(poisoned))),
                                        quarantine=True)
        caught = len(poisoned) - len(kept)
    finally:
        if not tel_was_enabled:
            t.set_enabled(False)

    _p(f"modelwatch_overhead: {rounds}+{rounds} rounds (work x{work_reps}, "
       f"fold {fold_s * 1e3:.2f}ms), plain {med_plain * 1e3:.1f}ms vs "
       f"watched {med_watched * 1e3:.1f}ms per round "
       f"({overhead_pct:+.4f}%), detection caught {caught}/2")

    if traces1 != traces0:
        raise BenchIntegrityError(
            f"modelwatch_overhead: trace counters moved during the timed "
            f"loops ({traces0} -> {traces1}) — the fused watch fold "
            "recompiled; refusing to publish")
    if caught != 2:
        raise BenchIntegrityError(
            f"modelwatch_overhead: quarantine screen caught {caught}/2 "
            "injected divergent clients — the watcher is not watching; "
            "refusing to publish")
    tol_pct = float(os.environ.get("FEDML_MODELWATCH_OVERHEAD_TOL_PCT", "1.0"))
    if overhead_pct >= tol_pct:
        raise BenchIntegrityError(
            f"modelwatch_overhead: fold-boundary stats consumed "
            f"{overhead_pct:.4f}% of the round wall (>= {tol_pct}%); "
            "always-on observability must be ~free; refusing to publish")

    return {
        "modelwatch_overhead_pct": round(max(overhead_pct, 0.0), 4),
        "modelwatch_plain_round_ms": round(med_plain * 1e3, 3),
        "modelwatch_watched_round_ms": round(med_watched * 1e3, 3),
        "modelwatch_fold_ms": round(fold_s * 1e3, 3),
        "modelwatch_rounds": rounds,
        "modelwatch_clients": clients,
        "modelwatch_work_reps": work_reps,
        "modelwatch_detection_caught": caught,
    }


def _bench_secagg_overhead():
    """Windowed SecAgg + accounted-DP fold overhead (ISSUE 20): per publish
    window the cohort runs key exchange + Shamir share dealing, each client
    quantizes and masks its update into the ring, and the server's publish
    unmasks, dequantizes, and DP-noises through the fused kernel. Privacy
    that makes the async buffer unaffordable would never be switched on —
    so, like modelwatch_overhead, this drives a round-SHAPED loop
    (calibrated numpy work standing in for local training, then the fold)
    once plain and once masked+noised, and bills the paired difference in
    round walls.

    Integrity guards (BenchIntegrityError, refusing to publish):
    - overhead: masked-vs-plain round wall delta must stay under
      FEDML_SECAGG_OVERHEAD_TOL_PCT (default 5%);
    - mask-off parity: with no privacy session attached the buffer's
      publish must stay bit-identical before and after the masked rounds
      (the subsystem must not perturb the plain path in-process);
    - masked parity: a zero-dropout window (no DP) must unmask to the
      honest quantized fold bit-exactly — masks that do not cancel make
      the overhead figure meaningless;
    - accountant liveness: the DP accountant must have stepped once per
      noised publish with epsilon_spent > 0."""
    import numpy as np

    from fedml_tpu.core.aggregation.async_buffer import (AsyncAggBuffer,
                                                         StalenessPolicy)
    from fedml_tpu.core.privacy import (DPFold, QuantSpec, WindowCoordinator,
                                        ring_bits_for)
    from fedml_tpu.core.privacy.masking import dequantize_sum, quantize_vector
    from fedml_tpu.utils.pytree import tree_flatten_to_vector

    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    dim = 64 if tiny else 192
    clients = 6 if tiny else 10
    rounds = 6 if tiny else 12
    work_ratio = 30.0  # train:fold wall ratio — local training dominates

    rng = np.random.default_rng(0)

    def _tree():
        return {"w": rng.standard_normal((dim, dim)).astype(np.float32),
                "b": rng.standard_normal((dim,)).astype(np.float32)}

    def _flat(tr):
        return np.asarray(tree_flatten_to_vector(tr)[0])

    template = {"w": np.zeros((dim, dim), np.float32),
                "b": np.zeros((dim,), np.float32)}
    spec = QuantSpec(ring_bits=ring_bits_for(clients, clients))
    deltas = [_tree() for _ in range(clients)]

    def _plain_buffer():
        return AsyncAggBuffer(publish_k=clients,
                              policy=StalenessPolicy(exponent=0.0))

    def _fold_plain(buf):
        for r in range(clients):
            buf.submit(r, deltas[r], 1.0, client_version=buf.version)
        return buf.publish()

    def _fold_masked(co, buf):
        _, members = co.open_window(range(clients))
        for r in range(clients):
            co.submit(r, members[r].mask(_flat(deltas[r])),
                      client_version=buf.version)
        return buf.publish()

    # mask-off parity reference + plain-arm warmup (compiles the fold)
    plain_before = _flat(_fold_plain(_plain_buffer()))

    # masked parity (no DP, zero dropout): masks must cancel bit-exactly
    pbuf = _plain_buffer()
    pco = WindowCoordinator(pbuf, template, spec=spec,
                            rng=np.random.default_rng(1))
    masked_out = _flat(_fold_masked(pco, pbuf))
    honest = dequantize_sum(
        sum(quantize_vector(_flat(d), spec) for d in deltas), clients, spec)
    if not np.array_equal(masked_out, honest):
        raise BenchIntegrityError(
            "secagg_overhead: zero-dropout window did not unmask to the "
            "honest quantized fold bit-exactly — masks are not cancelling; "
            "the overhead figure would be meaningless; refusing to publish")

    # the timed masked arm: secagg + accounted DP, one coordinator reused
    # across windows like a real server front
    mbuf = _plain_buffer()
    dp = DPFold(noise_multiplier=0.8, l2_clip=1.0, seed=0)
    mco = WindowCoordinator(mbuf, template, spec=spec, dp=dp,
                            rng=np.random.default_rng(2))
    tbuf = _plain_buffer()
    _fold_masked(mco, mbuf)  # warmup: compiles the fused noise kernel

    # calibrate round-shaped work off the plain fold wall
    fold_samples = []
    for _ in range(3):
        f0 = time.perf_counter()
        _fold_plain(_plain_buffer())
        fold_samples.append(time.perf_counter() - f0)
    fold_s = max(float(np.median(fold_samples)), 1e-5)
    work_elems = 512
    a = rng.standard_normal((work_elems, work_elems))
    b = rng.standard_normal((work_elems, work_elems))
    w0 = time.perf_counter()
    a = a @ b / float(work_elems)
    unit_s = max(time.perf_counter() - w0, 1e-7)
    round_s = max(work_ratio * fold_s, 0.8)
    work_reps = max(1, min(4000, int(round_s / unit_s)))

    # interleave plain/masked rounds so machine drift hits both arms of
    # each pair equally; the guard compares paired-difference medians
    steps0 = dp.accountant.steps
    plain_walls, masked_walls = [], []
    for _ in range(rounds):
        r0 = time.perf_counter()
        for _ in range(work_reps):       # the "local training" itself
            a = a @ b / float(work_elems)
        _fold_plain(tbuf)
        t1 = time.perf_counter()
        for _ in range(work_reps):
            a = a @ b / float(work_elems)
        _fold_masked(mco, mbuf)
        t2 = time.perf_counter()
        plain_walls.append(t1 - r0)
        masked_walls.append(t2 - t1)
    if not np.isfinite(a).all():           # keep the matmul live
        raise BenchIntegrityError("secagg_overhead: workload diverged")

    med_plain = float(np.median(plain_walls))
    med_masked = float(np.median(masked_walls))
    delta_s = float(np.median(np.asarray(masked_walls) -
                              np.asarray(plain_walls)))
    overhead_pct = 100.0 * delta_s / med_plain

    # mask-off parity: the plain path must be bit-identical after all the
    # masked windows ran in-process
    plain_after = _flat(_fold_plain(_plain_buffer()))
    if not np.array_equal(plain_before, plain_after):
        raise BenchIntegrityError(
            "secagg_overhead: the mask-off fold changed bit pattern after "
            "masked windows ran — the privacy subsystem perturbed the "
            "plain path; refusing to publish")

    eps = float(dp.accountant.epsilon_spent)
    noised = dp.accountant.steps - steps0
    _p(f"secagg_overhead: {rounds}+{rounds} rounds (work x{work_reps}, "
       f"fold {fold_s * 1e3:.2f}ms, d={dim * dim + dim}), plain "
       f"{med_plain * 1e3:.1f}ms vs masked+dp {med_masked * 1e3:.1f}ms per "
       f"round ({overhead_pct:+.4f}%), eps_spent {eps:.3f}")

    if noised != rounds or eps <= 0.0:
        raise BenchIntegrityError(
            f"secagg_overhead: accountant stepped {noised}x for {rounds} "
            f"noised publishes (eps {eps}) — DP is not being accounted; "
            "refusing to publish")
    tol_pct = float(os.environ.get("FEDML_SECAGG_OVERHEAD_TOL_PCT", "5.0"))
    if overhead_pct >= tol_pct:
        raise BenchIntegrityError(
            f"secagg_overhead: masking+DP consumed {overhead_pct:.4f}% of "
            f"the round wall (>= {tol_pct}%); privacy this expensive would "
            "never be switched on; refusing to publish")

    return {
        "secagg_overhead_pct": round(max(overhead_pct, 0.0), 4),
        "secagg_plain_round_ms": round(med_plain * 1e3, 3),
        "secagg_masked_round_ms": round(med_masked * 1e3, 3),
        "secagg_fold_ms": round(fold_s * 1e3, 3),
        "secagg_rounds": rounds,
        "secagg_clients": clients,
        "secagg_model_dim": dim * dim + dim,
        "dp_epsilon_spent": round(eps, 4),
        "dp_noise_multiplier": dp.noise_multiplier,
    }


def _bench_devperf_overhead(reps: int = 40):
    """Devperf registry overhead + live-vs-analytic MFU parity (ISSUE 17).

    Runs a real (tiny-aware) llama train step instrumented through
    ``devperf.instrument`` with the SAME analytic FLOPs/token hint bench's
    own MFU pipeline uses, folds each measured step via ``observe_step``,
    and publishes:

    - ``llm_mfu``: the registry's aggregate MFU — the number /statusz and
      ``fedml_device_mfu`` would show for this run;
    - ``llm_mfu_analytic``: bench's ``_mfu_from_rate`` on the same window —
      the two must agree within 15% (integrity-guarded) or the live fold
      arithmetic has drifted from the published pipeline;
    - ``devperf_overhead_pct``: the registry's self-accounted cost (AOT
      capture extraction + folds + HBM sampler sweeps) as a share of loop
      wall — must stay under FEDML_DEVPERF_OVERHEAD_TOL_PCT (default 1%).

    Zero-recompile is integrity-guarded: the instrumented step's AOT
    capture must be the ONE trace (``jax.compiles.bench_devperf_step`` == 1
    after the full loop)."""
    import jax
    import jax.numpy as jnp
    import optax

    from fedml_tpu.core import telemetry as tel
    from fedml_tpu.core.telemetry import devperf
    from fedml_tpu.parallel.fsdp import causal_lm_loss

    if not devperf.enabled():
        return {"skipped": "devperf_disabled"}
    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    reps = 12 if tiny else reps

    t = tel.get_telemetry()
    tel_was_enabled = t.enabled
    t.set_enabled(True)
    t.reset()
    devperf.reset()

    model, cfg, params = _build_llm("xla", remat=False)
    s = _llm_shape()
    vocab, seq, bs = s["vocab"], s["seq"], s["bs"]
    n_params = sum(x.size for x in jax.tree.leaves(params))
    tokens_per_step = bs * seq
    analytic_step_flops = _analytic_llm_step_flops(dict(s, bs=bs), n_params)
    tx = optax.adamw(1e-4)
    opt_state = tx.init(params)

    def body(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(model.apply({"params": p}, tokens), tokens)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    step = jax.jit(tel.track_compiles(body, name="bench_devperf_step"))
    fn = devperf.instrument(
        step, "bench_devperf",
        flops_per_token_hint=analytic_step_flops / tokens_per_step)

    rng = np.random.default_rng(0)
    batches = [jnp.asarray(rng.integers(0, vocab, (bs, seq)).astype(np.int32))
               for _ in range(reps + 1)]
    try:
        sampler = devperf.start_hbm_sampler(interval_s=0.05)
        _p(f"devperf_overhead: capture + warmup ({n_params/1e6:.0f}M params, "
           f">= {reps} reps)")
        p, o, loss = fn(params, opt_state, batches[reps])  # AOT capture
        float(loss)

        # bill only overhead accrued DURING the measured window: the sampler
        # also sweeps through compile/warmup above, and charging that against
        # the loop's wall would indict time the loop never spent
        overhead_ms0 = float(devperf.snapshot()["overhead_ms"])
        wall0 = time.perf_counter()
        dts = []
        done = 0
        # at least `reps` steps AND >= 1.5s of wall: a tiny-mode step is
        # ~20ms, and a sub-second window makes the fixed-cadence sampler's
        # handful of sweeps look like percent-scale overhead
        while done < reps or time.perf_counter() - wall0 < 1.5:
            r0 = time.perf_counter()
            p, o, loss = fn(p, o, batches[done % len(batches)])
            float(loss)  # scalar fetch: forces step completion
            dt = time.perf_counter() - r0
            dts.append(dt)
            devperf.observe_step("bench_devperf", dt, tokens=tokens_per_step)
            done += 1
            if done >= reps * 200:                 # pathological-fast guard
                break
        wall_s = time.perf_counter() - wall0
        reps = done
        overhead_pct = 100.0 * (
            (float(devperf.snapshot()["overhead_ms"]) - overhead_ms0)
            / 1e3) / wall_s
        if sampler is not None:
            sampler.sample_once()  # >= 1 sweep even on a sub-interval run

        compiles = tel.compile_count("bench_devperf_step")
        snap = devperf.snapshot()
        rec = snap["programs"].get("bench_devperf") or {}
        hbm_samples = int(snap["sampler"]["samples"])
    finally:
        devperf.stop_hbm_sampler()
        devperf.reset()
        if not tel_was_enabled:
            t.set_enabled(False)

    if compiles != 1:
        raise BenchIntegrityError(
            f"devperf_overhead: instrumented step traced {compiles}x (want "
            "exactly 1 — the AOT capture must BE the jit's one trace); "
            "refusing to publish")
    if not rec.get("captured") or int(rec.get("steps") or 0) != reps:
        raise BenchIntegrityError(
            f"devperf_overhead: registry never captured/folded the step "
            f"(captured {rec.get('captured')}, steps {rec.get('steps')}); "
            "overhead figure is meaningless; refusing to publish")

    # registry aggregate MFU vs bench's published tokens/sec -> MFU pipeline
    # on the SAME window: same FLOPs hint + same peak table, so disagreement
    # means the fold arithmetic drifted
    peak = float(rec["peak_flops_per_sec"])
    mfu_registry = (analytic_step_flops * reps) / (
        float(rec["device_seconds"]) * peak)
    mean_dt = sum(dts) / len(dts)
    mfu_analytic = _mfu_from_rate(
        tokens_per_step / mean_dt, analytic_step_flops, tokens_per_step, peak)
    rel_err = abs(mfu_registry / mfu_analytic - 1.0)
    _check_mfu("devperf_overhead", mfu_registry)
    xla_ratio = (float(rec["flops_xla"]) / analytic_step_flops
                 if rec.get("flops_xla") else None)

    _p(f"devperf_overhead: {reps} steps in {wall_s:.2f}s, registry MFU "
       f"{mfu_registry:.4f} vs analytic {mfu_analytic:.4f} "
       f"(rel err {100.0 * rel_err:.2f}%), overhead "
       f"{overhead_pct:.4f}% of wall, {hbm_samples} hbm sweeps")

    if rel_err > 0.15:
        raise BenchIntegrityError(
            f"devperf_overhead: registry MFU {mfu_registry:.4f} vs bench "
            f"analytic {mfu_analytic:.4f} (rel err {100.0 * rel_err:.1f}% > "
            "15%) — the live fold arithmetic disagrees with the published "
            "MFU pipeline; refusing to publish")
    tol_pct = float(os.environ.get("FEDML_DEVPERF_OVERHEAD_TOL_PCT", "1.0"))
    if overhead_pct >= tol_pct:
        raise BenchIntegrityError(
            f"devperf_overhead: registry consumed {overhead_pct:.4f}% of the "
            f"step-loop wall (>= {tol_pct}%); always-on observability must "
            "be ~free; refusing to publish")

    return {
        "llm_mfu": round(mfu_registry, 6),
        "llm_mfu_analytic": round(mfu_analytic, 6),
        "llm_mfu_rel_err": round(rel_err, 6),
        "devperf_overhead_pct": round(overhead_pct, 4),
        "devperf_flops_source": rec.get("flops_source"),
        "devperf_xla_vs_analytic_flops_ratio": (
            round(xla_ratio, 4) if xla_ratio is not None else None),
        "devperf_roofline_verdict": rec.get("roofline_verdict"),
        "devperf_steps": reps,
        "devperf_window_s": round(wall_s, 2),
        "devperf_hbm_samples": hbm_samples,
    }


def _bench_placement_search(probe_publishes: int = 4, reps: int = 2):
    """Auto-placement search (ISSUE 11): cost-model-seeded, measurement-
    refined search (core/engine/placement_search.py) vs the hand-picked
    defaults, on TWO workloads sharing one BucketedAggregator:

    - async_fedbuff: search (publish_k x staleness exponent) with short
      AsyncEventSim probes; headline rounds/hr. The hand-picked default is
      the async_rounds stage's own config (publish_k=32, exponent=0.5).
    - sync_agg: search the execution strategy (per-client sequential
      dispatch vs one megabatch fold); headline clients/sec. The
      hand-picked default is the sp front's in_process_sequential.

    The winning PlacementPlan per workload is written as a committed JSON
    artifact (PLACEMENT_PLAN_<workload>.json — bench_watch commits it next
    to BENCH_MEASURED_*) so `args.placement=/path/to/plan.json` replays the
    searched config without re-probing.

    Integrity guards (BenchIntegrityError, refusing to publish):
    - the searched winner must beat its baseline on >= 1 workload headline;
    - zero retraces: a warmup search compiles every program any probed
      candidate needs; the timed search must not move the engine's
      accumulate trace counters (the searched config is a re-wiring of the
      SAME compiled folds, not a new program)."""
    import jax

    from fedml_tpu.core.aggregation.async_buffer import AsyncAggBuffer, StalenessPolicy
    from fedml_tpu.core.aggregation.bucketed import BucketedAggregator
    from fedml_tpu.core.engine import (
        STRATEGY_IN_PROCESS,
        STRATEGY_VMAPPED,
        PlacementCandidate,
        PlacementSearch,
        WorkloadProfile,
        enumerate_candidates,
    )
    from fedml_tpu.simulation.vmapped.async_driver import (
        AsyncEventSim,
        DelayModel,
        make_synthetic_delta_fn,
    )

    dev = jax.devices()[0]
    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    bucket = 16
    eng = BucketedAggregator(bucket)
    n_clients = 200 if tiny else 2000

    # same ~100k-param MLP-shaped pytree as the async_rounds stage — the
    # search compares PLACEMENTS of one workload, so the model is fixed
    key = np.random.default_rng(5)
    template = {
        "dense1": {"kernel": np.asarray(key.standard_normal((128, 256)), np.float32),
                   "bias": np.zeros((256,), np.float32)},
        "dense2": {"kernel": np.asarray(key.standard_normal((256, 256)), np.float32),
                   "bias": np.zeros((256,), np.float32)},
        "head": {"kernel": np.asarray(key.standard_normal((256, 64)), np.float32),
                 "bias": np.zeros((64,), np.float32)},
    }
    template = jax.device_put(template)
    model_bytes = int(sum(np.asarray(l).nbytes for l in jax.tree.leaves(template)))
    gen = make_synthetic_delta_fn(seed=11)

    # --- workload A: async FedBuff, headline rounds/hr ---------------------
    async_prof = WorkloadProfile(
        name="async_fedbuff", cohort_size=n_clients, model_bytes=model_bytes,
        is_async=True, headline="rounds_per_hr")
    # hand-picked default: exactly what _bench_async_rounds runs today
    async_default = PlacementCandidate(
        strategy=STRATEGY_VMAPPED, publish_k=32, staleness_exponent=0.5)

    def probe_async(cand):
        best = None
        for r in range(reps):
            sim = AsyncEventSim(
                AsyncAggBuffer(
                    publish_k=int(cand.publish_k or 32),
                    policy=StalenessPolicy(
                        exponent=float(cand.staleness_exponent or 0.0)),
                    engine=eng),
                gen, n_clients, initial_model=template,
                delay=DelayModel(n_clients, mean_delay=1.0, heterogeneity=0.5,
                                 seed=1000 + r),
                gen_batch=256)
            stats = sim.run(probe_publishes)
            if best is None or stats["server_seconds"] < best:
                best = stats["server_seconds"]
        return probe_publishes / best * 3600.0

    async_cands = enumerate_candidates(
        async_prof, max_devices=1, publish_ks=(8, 16, 32, 64),
        staleness_exponents=(0.0, 0.5))

    # --- workload B: sync cohort aggregation, headline clients/sec ---------
    sync_prof = WorkloadProfile(
        name="sync_agg", cohort_size=2 * bucket, model_bytes=model_bytes,
        is_async=False, headline="clients_per_sec")
    # hand-picked default: the sp front's per-client sequential dispatch
    sync_default = PlacementCandidate(strategy=STRATEGY_IN_PROCESS)
    ids = np.arange(2 * bucket, dtype=np.int32)
    stacked = gen(template, ids, 0)
    cohort = [(float(k + 1),
               jax.tree.map(lambda l, _k=k: l[_k], stacked))
              for k in range(2 * bucket)]

    def probe_sync(cand):
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            if cand.strategy == STRATEGY_IN_PROCESS:
                for w, tree in cohort:   # one dispatch per client
                    eng.aggregate([(w, tree)])
            else:
                eng.aggregate(cohort)    # one megabatch fold per bucket
            dt = time.perf_counter() - t0
            if best is None or dt < best:
                best = dt
        return len(cohort) / best

    sync_cands = enumerate_candidates(sync_prof, max_devices=1)

    def run_search():
        plans = {}
        plans["async_fedbuff"] = PlacementSearch(
            async_prof, probe_async, candidates=async_cands, probe_top_n=3,
            baseline=async_default).search()
        plans["sync_agg"] = PlacementSearch(
            sync_prof, probe_sync, candidates=sync_cands, probe_top_n=2,
            baseline=sync_default).search()
        return plans

    _p(f"placement bench: warmup search ({len(async_cands)} async + "
       f"{len(sync_cands)} sync candidates, {n_clients} clients)")
    run_search()  # compiles every fold program any probed candidate touches
    traces_before = int(eng.accum_traces)

    _p("placement bench: timed search")
    plans = run_search()

    if eng.accum_traces != traces_before:
        raise BenchIntegrityError(
            f"placement probes retraced during the timed search "
            f"({traces_before} -> {eng.accum_traces}); the searched config "
            "must re-wire the SAME compiled folds; refusing to publish")

    plan_docs: dict = {}
    speedups: dict = {}
    plan_files: list = []
    for workload, ranked in plans.items():
        win = ranked[0]
        fname = f"PLACEMENT_PLAN_{workload}.json"
        with open(fname, "w", encoding="utf-8") as f:
            f.write(win.to_json() + "\n")
        plan_files.append(fname)
        cand = win.candidate
        plan_docs[workload] = {
            "fingerprint": cand.fingerprint(),
            "strategy": cand.strategy,
            "publish_k": cand.publish_k,
            "staleness_exponent": cand.staleness_exponent,
            "headline": win.headline_metric,
            "measured": round(float(win.measured), 1),
            "baseline": round(float(win.baseline_value), 1),
        }
        speedups[workload] = round(float(win.speedup), 2)

    if max(speedups.values()) <= 1.0:
        raise BenchIntegrityError(
            f"placement search failed to beat the hand-picked default on any "
            f"workload ({speedups}); refusing to publish")

    return {
        "placement_plan": plan_docs,
        "placement_speedup": speedups,
        "placement_plan_files": plan_files,
        "placement_probe_publishes": probe_publishes,
        "placement_candidates": {"async_fedbuff": len(async_cands),
                                 "sync_agg": len(sync_cands)},
        "placement_accum_traces": int(eng.accum_traces),
        "device": getattr(dev, "device_kind", str(dev)),
    }


def _bench_llm_serving(n_replicas: int = 2, clients: int = 4, reqs_per_client: int = 3):
    """Endpoint-level decode throughput (BASELINE config 5): tokens/s
    measured THROUGH the gateway with subprocess replicas — the real
    deployment topology (gateway retry/eviction + HTTP + per-replica
    KV-cache decode), unlike the in-process decode bench.

    Round 4: the replicas serve the FLAGSHIP 268M llama proxy (VERDICT r3
    missing #4 — the old bench served a ~30M toy), each replica pinned to a
    fixed HBM fraction via XLA_PYTHON_CLIENT_MEM_FRACTION so two replicas
    coexist deterministically. If the full replica count can never become
    ready inside the budget, the bench degrades to however many replicas ARE
    ready (>=1) and reports the actual count, rather than dying.

    The gateway round-robins whole requests to replicas (reference
    device_model_inference.py does the same); each replica additionally
    runs server-side DYNAMIC BATCHING (10ms window, max 4 — the
    _MicroBatcher the reference lacks), so concurrency is absorbed by both
    replica parallelism and in-replica batch decode. Distinct prompts per
    request so the platform cannot dedupe executions."""
    import threading

    from fedml_tpu.serving.replica_controller import InferenceGateway, ReplicaSet

    # the warm-up/measured prompts rely on single-digit fields tokenizing to
    # the same length (and 'req 9' being reserved for warm-up)
    if clients > 10 or reqs_per_client > 9:
        raise ValueError("serving bench supports clients <= 10 and reqs_per_client <= 9")

    # env mutation only after all validation: a raise must not leak batching
    # settings into the process
    saved_env = {k: os.environ.get(k) for k in
                 ("FEDML_SERVE_MAX_BATCH", "FEDML_SERVE_BATCH_WINDOW_MS",
                  "FEDML_REPLICA_MEM_FRACTION", "FEDML_BENCH_FLAGSHIP",
                  "FEDML_COMPILE_CACHE_DIR")}
    os.environ["FEDML_SERVE_MAX_BATCH"] = "4"  # inherited by replica children
    os.environ["FEDML_SERVE_BATCH_WINDOW_MS"] = "10"
    # replicas pay the window's costliest cold compiles; the shared persistent
    # cache (replica_main.py reads this env) lets a SECOND window skip them
    from fedml_tpu.utils.compile_cache import cache_dir

    os.environ["FEDML_COMPILE_CACHE_DIR"] = cache_dir()
    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    if not tiny:
        os.environ["FEDML_BENCH_FLAGSHIP"] = "1"  # 268M predictor geometry
        # ~0.5GB bf16 params + KV caches per replica; 0.35 of a 16G v5e each
        # leaves headroom for compile scratch while keeping 2 replicas co-resident
        os.environ.setdefault("FEDML_REPLICA_MEM_FRACTION", "0.35")

    # matches bench_predictors' default_max_new_tokens (tiny mode is the
    # CPU test harness for this path)
    new_tokens = 16 if tiny else 64
    # round 4: startup budget capped (VERDICT r3 weak #2 — 2x900s startup ate
    # most of a capture window); flagship compile lands well under this. The
    # orchestrator's serving stage budget must stay above the serial sum of
    # these (see _STAGES).
    startup_budget_s = 60.0 if tiny else 300.0
    predict_timeout_s = 60.0 if tiny else 240.0
    rs = None
    try:
        rs = ReplicaSet(
            "fedml_tpu.serving.bench_predictors:llm_bench_predictor",
            desired=n_replicas, startup_timeout_s=startup_budget_s,
        )
        deadline = time.time() + startup_budget_s  # fedlint: disable=wall-clock startup deadline shared with replica subprocesses
        while time.time() < deadline:  # fedlint: disable=wall-clock startup deadline shared with replica subprocesses
            if len([r for r in rs.healthy() if r.ready()]) >= n_replicas:
                break
            time.sleep(1.0)  # fedlint: disable=bare-sleep replica startup poll pacing, not a retry
            rs.reconcile()  # replace replicas that died during startup
        ready = [r for r in rs.healthy() if r.ready()]
        if not ready:
            raise RuntimeError("serving bench: no replica became ready in budget")
        n_ready = len(ready)
        if n_ready < n_replicas:
            print(f"warning: only {n_ready}/{n_replicas} replicas ready; "
                  "measuring with what we have", file=sys.stderr)
            # degrade to the replicas that ARE ready — prune BY READINESS
            # (scale_to would pop the newest replica, ready or not)
            rs.retain(ready)
        gw = InferenceGateway(rs)
        # warm EVERY replica with the measured prompt SHAPE: generate()
        # compiles per prompt token-length, so the warm prompts must
        # tokenize to the same length as the measured ones ('measure
        # endpoint run {c} req {r}') or the timed window absorbs a fresh
        # prefill compile on each replica; round-robin spreads these
        for w in range(n_ready):
            # single-digit fields keep the token length identical to the
            # measured prompts; 'req 9' never occurs in the measured set
            gw.predict({"prompt": f"measure endpoint run {w % 10} req 9"},
                       timeout_s=predict_timeout_s)

        results: list = []
        errors: list = []

        def client(cid: int) -> None:
            try:
                for r in range(reqs_per_client):
                    out = gw.predict({"prompt": f"measure endpoint run {cid} req {r}"},
                                     timeout_s=predict_timeout_s)
                    results.append(out)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(c,)) for c in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errors:
            raise RuntimeError(f"serving bench request failed: {errors[0]!r}")
        total_new = new_tokens * len(results)
        return {
            "endpoint_decode_tokens_per_sec": total_new / dt,
            "endpoint_replicas": n_ready,
            "endpoint_requests": len(results),
            "endpoint_model": "tiny" if tiny else "llama-268M flagship proxy (bf16)",
            "endpoint_batching": "dynamic (per-replica micro-batch, window 10ms, max 4)",
            # int8 weight-only mode (serving/quant.py) is opt-in; the label
            # keeps a quantized measurement from ever reading as fp
            "endpoint_weight_quant": (
                "int8" if os.environ.get("FEDML_BENCH_INT8") == "1" else "none"),
        }
    finally:
        if rs is not None:
            rs.shutdown()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _serving_load_prompts(streams: int, tiny: bool, seed: int = 0):
    """The load mix: RAGGED prompt/output lengths, and 70% of streams share
    one of 4 system prompts (the production shape paged prefix sharing
    exists for: few long system prompts, many short user tails)."""
    import random

    rng = random.Random(seed)
    base = "federated benchmark serving endpoint throughput measure "
    # tiny cfg has max_seq_len 64: one base rep keeps prompt+max_new inside
    # the context while the shared system prefix still spans 2+ full pages
    sys_reps = 1 if tiny else 8
    system = [f"system prompt {w}: " + base * sys_reps
              for w in ("alpha", "beta", "gamma", "delta")]
    reqs = []
    for i in range(streams):
        tail = f"user {i % 97} asks question {i % 7} about topic {i % 13}"
        if rng.random() < 0.70:
            prompt = system[i % 4] + tail
        else:
            prompt = f"cold prompt {i}: " + base * rng.randint(1, sys_reps) + tail
        max_new = rng.randint(2, 8) if tiny else rng.randint(4, 32)
        reqs.append({"prompt": prompt, "max_new_tokens": max_new})
    return reqs


def _serving_load_once(reqs: list, paged: bool):
    """One load run: `len(reqs)` concurrent HTTP streams against a fresh
    in-process runner + engine (paged or fixed-slot, selected via the env
    seam the predictor reads). Returns the metrics of this run."""
    import http.client
    import threading

    from fedml_tpu.serving.bench_predictors import llm_bench_predictor
    from fedml_tpu.serving.fedml_inference_runner import FedMLInferenceRunner

    streams = len(reqs)
    runner = None
    os.environ["FEDML_SERVE_PAGED"] = "1" if paged else "0"
    os.environ["FEDML_SERVE_CONTINUOUS"] = "0" if paged else "1"
    try:
        pred = llm_bench_predictor()  # warmed (engine compiles in warmup)
        assert pred.engine is not None, "continuous engine did not come up"
        runner = FedMLInferenceRunner(pred, port=0)
        port = runner.start()

        ok: list = []
        failures: list = []
        start_gate = threading.Event()

        def stream(i: int) -> None:
            # keep-alive connection per stream; one long-lived decode each,
            # so `streams` requests really are concurrently in flight. The
            # ramp (200 connects per 50ms tranche) keeps 10k near-simultaneous
            # TCP connects from overflowing the server's accept backlog —
            # every stream is still concurrently IN FLIGHT, admission just
            # sees an arrival wave instead of a SYN flood.
            start_gate.wait()
            time.sleep((i // 200) * 0.05)  # fedlint: disable=bare-sleep connect-ramp pacing, not a retry
            try:
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=900)
                conn.request("POST", "/predict", json.dumps(reqs[i]),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                data = resp.read()
                conn.close()
                if resp.status != 200:
                    raise RuntimeError(f"status {resp.status}: {data[:200]!r}")
                ok.append(json.loads(data))
            except Exception as e:  # noqa: BLE001 - tallied, stage-fatal below
                failures.append(repr(e))

        base = pred.engine.stats()["tokens_out"]
        threads = [threading.Thread(target=stream, args=(i,)) for i in range(streams)]
        # sample slot occupancy / queue depth / KV pages DURING the load
        # (stats() after join always reads 0 — the interesting number is
        # mid-burst)
        occ_samples: list = []
        q_samples: list = []
        ppt_samples: list = []  # kv pages per live token (paged only)
        done_gate = threading.Event()

        def sampler() -> None:
            start_gate.wait()
            while not done_gate.wait(0.05):
                s = pred.engine.stats()
                occ_samples.append(s["slot_occupancy"])
                q_samples.append(s["queue_depth"])
                if paged and s.get("kv_tokens_live", 0) > 0:
                    ppt_samples.append(s["kv_pages_per_token"])

        samp = threading.Thread(target=sampler, daemon=True)
        samp.start()
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        start_gate.set()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        done_gate.set()
        samp.join(timeout=2)
        st = pred.engine.stats()
        pct = pred.engine.latency_percentiles()
        if failures:
            # acceptance is "without request failures": any failure is a
            # stage failure, with the first few causes in the record
            raise RuntimeError(
                f"serving_load[{'paged' if paged else 'fixed'}]: "
                f"{len(failures)}/{streams} streams failed: "
                + "; ".join(failures[:3]))
        tokens = st["tokens_out"] - base
        cfg = pred._cfg
        # KV bytes actually provisioned by this engine (k+v, all layers)
        import numpy as _np

        per_tok = (2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim
                   * _np.dtype(cfg.dtype).itemsize)
        kv_tokens = (st["kv_pages_total"] * st["kv_page_size"] if paged
                     else st["slots_total"] * cfg.max_seq_len)
        return {
            "tokens_per_sec": round(tokens / dt, 2),
            "tokens": tokens,
            "wall_s": round(dt, 2),
            "ttft_p50_s": pct["ttft_s"]["p50"],
            "ttft_p99_s": pct["ttft_s"]["p99"],
            "tpot_p50_s": pct["tpot_s"]["p50"],
            "tpot_p99_s": pct["tpot_s"]["p99"],
            "slots": st["slots_total"],
            "chunk": st["chunk"],
            "occ_peak": round(max(occ_samples), 3) if occ_samples else None,
            "occ_mean": (round(sum(occ_samples) / len(occ_samples), 3)
                         if occ_samples else None),
            "queue_peak": max(q_samples) if q_samples else None,
            "kv_tokens": kv_tokens,
            "kv_bytes": kv_tokens * per_tok,
            "kv_pages_per_token": (
                round(sum(ppt_samples) / len(ppt_samples), 4)
                if ppt_samples else None),
            "prefix_hits": st.get("kv_prefix_hits"),
            "prefix_misses": st.get("kv_prefix_misses"),
            "alloc_deferred": st.get("kv_alloc_deferred"),
        }
    finally:
        if runner is not None:
            runner.stop()


def _bench_llm_serving_load(streams: int | None = None):
    """Load test: 10k CONCURRENT streams against ONE endpoint, run TWICE —
    paged KV engine vs fixed-slot engine — on the identical ragged
    shared-prefix workload (serving/continuous_batching.py, paged_kv.py).

    Topology: one in-process FedMLInferenceRunner (stdlib threading HTTP
    server) over an LLMPredictor. In-process (no subprocess replicas)
    because the claim under test is the ENGINE's ability to interleave the
    streams on one chip; the `serving` stage keeps covering the
    multi-replica topology.

    The paged engine is deliberately given HALF the fixed engine's KV
    provisioning (num_pages * page_size = slots * max_seq_len / 2): the
    claim is that prefix sharing + token-granular paging beat worst-case
    row allocation on BOTH axes at once — p99 TTFT (queue wait dominates
    at this concurrency, and 70% of streams skip their system prompt's
    prefill) AND total KV HBM. Both claims are integrity-GUARDED
    (BenchIntegrityError) on the full-scale run; the tiny CPU harness
    records but does not guard TTFT (8 slots of timing noise)."""
    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    if streams is None:
        streams = int(os.environ.get("FEDML_SERVE_LOAD_STREAMS",
                                     "64" if tiny else "10240"))
    saved_env = {k: os.environ.get(k) for k in
                 ("FEDML_SERVE_CONTINUOUS", "FEDML_SERVE_PAGED",
                  "FEDML_SERVE_SLOTS", "FEDML_SERVE_CHUNK",
                  "FEDML_SERVE_PAGE_SIZE", "FEDML_SERVE_KV_PAGES",
                  "FEDML_SERVE_MAX_QUEUE", "FEDML_BENCH_FLAGSHIP")}
    slots = int(os.environ.setdefault("FEDML_SERVE_SLOTS",
                                      "8" if tiny else "64"))
    os.environ.setdefault("FEDML_SERVE_CHUNK", "4" if tiny else "16")
    os.environ["FEDML_SERVE_MAX_QUEUE"] = str(streams + 64)
    if not tiny:
        os.environ["FEDML_BENCH_FLAGSHIP"] = "1"  # 268M predictor geometry
    page_size = 16
    os.environ["FEDML_SERVE_PAGE_SIZE"] = str(page_size)
    max_seq = 64 if tiny else 256
    # HALF the fixed-slot KV budget (+1 for the reserved trash page)
    os.environ["FEDML_SERVE_KV_PAGES"] = str(
        slots * max_seq // page_size // 2 + 1)
    try:
        reqs = _serving_load_prompts(streams, tiny)
        paged = _serving_load_once(reqs, paged=True)
        fixed = _serving_load_once(reqs, paged=False)
        if paged["kv_bytes"] >= fixed["kv_bytes"]:
            raise BenchIntegrityError(
                f"paged engine provisioned {paged['kv_bytes']} KV bytes vs "
                f"fixed {fixed['kv_bytes']} — the HBM claim is void")
        if (not tiny and paged["ttft_p99_s"] is not None
                and fixed["ttft_p99_s"] is not None
                and paged["ttft_p99_s"] >= fixed["ttft_p99_s"]):
            raise BenchIntegrityError(
                f"paged p99 TTFT {paged['ttft_p99_s']:.3f}s did not beat "
                f"fixed-slot {fixed['ttft_p99_s']:.3f}s at {streams} streams "
                "— the latency claim is void")
        out = {
            "serving_load_streams": streams,
            "serving_load_tokens_per_sec": paged["tokens_per_sec"],
            "serving_load_tokens": paged["tokens"],
            "serving_load_wall_s": paged["wall_s"],
            "serving_load_ttft_p50_s": paged["ttft_p50_s"],
            # headline keys (bench_regress HEADLINES): paged-engine tails
            "serving_load_p99_ttft_s": paged["ttft_p99_s"],
            "serving_load_p99_tpot_s": paged["tpot_p99_s"],
            "kv_pages_per_token": paged["kv_pages_per_token"],
            "serving_load_slots": paged["slots"],
            "serving_load_chunk": paged["chunk"],
            "serving_load_slot_occupancy_peak": paged["occ_peak"],
            "serving_load_slot_occupancy_mean": paged["occ_mean"],
            "serving_load_queue_depth_peak": paged["queue_peak"],
            "serving_load_kv_bytes_paged": paged["kv_bytes"],
            "serving_load_kv_bytes_fixed": fixed["kv_bytes"],
            "serving_load_kv_hbm_ratio": round(
                paged["kv_bytes"] / fixed["kv_bytes"], 3),
            "serving_load_prefix_hits": paged["prefix_hits"],
            "serving_load_prefix_misses": paged["prefix_misses"],
            "serving_load_alloc_deferred": paged["alloc_deferred"],
            "serving_load_fixed_tokens_per_sec": fixed["tokens_per_sec"],
            "serving_load_fixed_ttft_p99_s": fixed["ttft_p99_s"],
            "serving_load_fixed_tpot_p99_s": fixed["tpot_p99_s"],
            "serving_load_model": "tiny" if tiny else "llama-268M flagship proxy (bf16)",
            "serving_load_engine": ("paged KV (prefix-shared, "
                                    "admission-pipelined) vs fixed-slot"),
        }
        for k in ("serving_load_ttft_p50_s", "serving_load_p99_ttft_s",
                  "serving_load_p99_tpot_s", "serving_load_fixed_ttft_p99_s",
                  "serving_load_fixed_tpot_p99_s"):
            if out[k] is not None:
                out[k] = round(out[k], 4)
        # legacy aliases (dashboards pre-paged): same values, old names
        out["serving_load_ttft_p99_s"] = out["serving_load_p99_ttft_s"]
        out["serving_load_tpot_p50_s"] = (
            round(paged["tpot_p50_s"], 4) if paged["tpot_p50_s"] is not None
            else None)
        out["serving_load_tpot_p99_s"] = out["serving_load_p99_tpot_s"]
        return out
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# --- workload A: ResNet-56 / CIFAR-10 local SGD ------------------------------

def _resnet56_fwd_flops_per_image(width: int = 16) -> float:
    """Analytic conv+fc FLOPs (2*MACs) for the 6n+2 CIFAR ResNet, 32x32 input."""
    flops = 2 * 32 * 32 * 9 * 3 * width  # stem
    n = (56 - 2) // 6
    hw, cin = 32 * 32, width
    for stage, cout in enumerate([width, 2 * width, 4 * width]):
        for block in range(n):
            if stage > 0 and block == 0:
                hw //= 4
                flops += 2 * hw * cin * cout  # 1x1 projection
            flops += 2 * hw * 9 * cin * cout + 2 * hw * 9 * cout * cout
            cin = cout
    flops += 2 * cin * 10  # fc
    return float(flops)


def _bench_resnet_tpu(reps: int = 10, bs: int = 128):
    import jax
    import jax.numpy as jnp
    import optax

    from fedml_tpu.models.resnet import ResNetCifar

    model = ResNetCifar(depth=56, num_classes=10)
    key = jax.random.PRNGKey(0)
    params = model.init(key, jnp.zeros((1, 32, 32, 3)))["params"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(0)
    # disjoint-index chains consume 0..reps+3, warmup reps+4 (see
    # _timed_chain: no timed dispatch may repeat one already issued)
    xs = [jnp.asarray(rng.normal(size=(bs, 32, 32, 3)).astype(np.float32)) for _ in range(reps + 5)]
    ys = [jnp.asarray(rng.integers(0, 10, bs).astype(np.int32)) for _ in range(reps + 5)]

    xla_flops = _cost_analysis_flops(step.lower(params, opt_state, xs[0], ys[0]).compile())
    float(step(params, opt_state, xs[reps + 4], ys[reps + 4])[2])  # warmup (excluded)

    def step_once(state, r):
        p, o = (params, opt_state) if state is None else (state[0], state[1])
        return step(p, o, xs[r], ys[r])

    dt_step = _timed_chain(step_once, 2, reps + 2)

    analytic_step_flops = 3.0 * _resnet56_fwd_flops_per_image() * bs  # fwd+bwd
    if xla_flops is not None and not (0.3 <= xla_flops / analytic_step_flops <= 3.0):
        print(
            f"warning: resnet XLA flops {xla_flops:.3e} vs analytic "
            f"{analytic_step_flops:.3e}; using analytic", file=sys.stderr,
        )
    dev = jax.devices()[0]
    peak = _chip_peak_tflops(dev, dtype_bits=16) * 1e12  # bf16: default TPU matmul precision
    mfu = (analytic_step_flops / dt_step) / peak
    _check_mfu("resnet56", mfu)

    # North-star metric (BASELINE.md acceptance): FedAvg ROUNDS/HR, measured
    # as a real sp-simulator-shaped round on-chip — N clients train from the
    # same global params on DISTINCT batches (serial, like simulation/sp),
    # then a jitted weighted average. Completion forced by fetching a scalar
    # of the aggregated tree (same honesty contract as the step chains).
    local_steps = 10

    @jax.jit
    def fedavg(trees):
        return jax.tree.map(lambda *ls: sum(ls) / len(ls), *trees)

    def fed_round(n_clients: int) -> float:
        """One serial FedAvg round (sp-simulator shape): every client trains
        from the same global params on its OWN freshly drawn batches — the
        rng keeps advancing, so no dispatch here repeats one from the
        steps/sec phase or an earlier round size (dedup honesty rule)."""
        _p(f"resnet bench: timing a FedAvg round ({n_clients} clients x "
           f"{local_steps} local steps)")
        cxs = [[jnp.asarray(rng.normal(size=(bs, 32, 32, 3)).astype(np.float32))
                for _ in range(local_steps)] for _ in range(n_clients)]
        cys = [[jnp.asarray(rng.integers(0, 10, bs).astype(np.int32))
                for _ in range(local_steps)] for _ in range(n_clients)]
        # warm the aggregation compile OUT of the timed round (the train
        # step is already warm from the steps/sec phase — same function,
        # same shapes; fedavg recompiles per client-list length)
        float(jax.tree.leaves(fedavg([params] * n_clients))[0].reshape(-1)[0])
        t0 = time.perf_counter()
        locals_ = []
        for c in range(n_clients):
            p, o = params, opt_state
            for s in range(local_steps):
                p, o, loss = step(p, o, cxs[c][s], cys[c][s])
            locals_.append(p)
        agg = fedavg(locals_)
        float(jax.tree.leaves(agg)[0].reshape(-1)[0])  # force the whole round
        return time.perf_counter() - t0

    n_headline = 4
    round_sec = fed_round(n_headline)
    out = {
        "steps_per_sec": 1.0 / dt_step, "mfu": mfu, "bs": bs,
        "fedavg_round_sec": round_sec,
        "fedavg_rounds_per_hr": 3600.0 / round_sec,
        "fedavg_clients": n_headline, "fedavg_local_steps": local_steps,
    }
    # the BASELINE.json acceptance names a 16-SILO FedAvg run; measure the
    # north-star vocabulary at that cohort size too (same compiled step).
    # Skipped in tiny dry-runs: 160 extra CPU train steps would threaten the
    # stage budget for a number the tiny artifact never publishes anyway.
    if os.environ.get("FEDML_BENCH_TINY") != "1":
        round16_sec = fed_round(16)
        out["fedavg16_round_sec"] = round16_sec
        out["fedavg16_rounds_per_hr"] = 3600.0 / round16_sec
    return out


def _bench_resnet_torch_cpu(bs: int = 32, budget_s: float = 60.0) -> float | None:
    """Same-model torch-CPU train step; returns IMAGES/sec (per-image
    normalization lets the CPU run a smaller batch than the TPU side —
    bs=128 on this image's single core would blow the bench budget)."""
    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    class Block(nn.Module):
        def __init__(self, cin, cout, stride):
            super().__init__()
            self.c1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
            self.g1 = nn.GroupNorm(8, cout)
            self.c2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
            self.g2 = nn.GroupNorm(8, cout)
            self.proj = (
                nn.Sequential(nn.Conv2d(cin, cout, 1, stride, bias=False), nn.GroupNorm(8, cout))
                if (stride != 1 or cin != cout) else None
            )

        def forward(self, x):
            r = self.proj(x) if self.proj else x
            y = self.g2(self.c2(F.relu(self.g1(self.c1(x)))))
            return F.relu(y + r)

    class ResNet56(nn.Module):
        def __init__(self, w=16):
            super().__init__()
            layers = [nn.Conv2d(3, w, 3, 1, 1, bias=False), nn.GroupNorm(8, w), nn.ReLU()]
            cin = w
            for stage, cout in enumerate([w, 2 * w, 4 * w]):
                for block in range(9):
                    layers.append(Block(cin, cout, 2 if stage > 0 and block == 0 else 1))
                    cin = cout
            self.body = nn.Sequential(*layers)
            self.fc = nn.Linear(cin, 10)

        def forward(self, x):
            return self.fc(self.body(x).mean(dim=(2, 3)))

    try:
        model = ResNet56()
        opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        rng = np.random.default_rng(0)
        x = torch.tensor(rng.normal(size=(bs, 3, 32, 32)).astype(np.float32))
        y = torch.tensor(rng.integers(0, 10, bs))

        def one_step():
            opt.zero_grad()
            F.cross_entropy(model(x), y).backward()
            opt.step()

        one_step()
        t0 = time.perf_counter()
        n = 0
        while (n < 3 or time.perf_counter() - t0 < 3.0) and time.perf_counter() - t0 < budget_s:
            one_step()
            n += 1
        return bs * n / (time.perf_counter() - t0)
    except Exception as e:
        print(f"warning: torch-CPU resnet baseline failed: {e}", file=sys.stderr)
        return None


def _probe_backend(timeout_s: int = 180) -> None:
    """Fail fast if the remote TPU tunnel is stalled: jax.devices() on the
    axon backend blocks forever in native code when the tunnel is down
    (uninterruptible by SIGALRM), which would eat the driver's whole bench
    timeout with no diagnostic. Probe in a killable subprocess BEFORE any
    stage subprocess is spawned.

    The probe (tools/tpu_probe.py, shared with bench_watch.sh) EXECUTES a
    jitted op and fetches the result — listing devices only exercises the
    tunnel's control plane, and a window where metadata answers but compute
    stalls (observed: every stage of a run hung while jax.devices() kept
    succeeding) must read as DOWN, not up."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "tpu_probe.py")],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        raise BenchProbeTimeout(
            f"jax backend init did not complete within {timeout_s}s — the "
            "remote TPU tunnel is stalled; rerun when it recovers"
        )
    if proc.returncode != 0:
        raise RuntimeError(f"jax backend init failed:\n{proc.stderr[-1000:]}")
    print(f"benching on {proc.stdout.strip().splitlines()[-1]}", file=sys.stderr)


def _last_measured() -> dict | None:
    """The most INFORMATIVE committed BENCH_MEASURED_*.json artifact, or
    None. These are written after every successful stage (see main)
    precisely so a tunnel stall mid-run still leaves an auditable,
    timestamped number in git. 'Most informative' = newest among the
    artifacts with the most stage records: a later headline-only artifact
    (an interrupted ladder's first increment) must not shadow an earlier
    full-ladder record in a skip report; the full artifact list rides
    along so nothing is hidden."""
    paths = sorted(glob.glob(os.path.join(_REPO, "BENCH_MEASURED_*.json")))
    if not paths:
        return None
    docs = []
    for p in paths:
        try:
            with open(p) as f:
                doc = json.load(f)
        except Exception:
            continue
        if isinstance(doc, dict):
            docs.append((p, doc))
    if not docs:
        return None

    def n_stages(doc: dict) -> int:
        # stage records are the '_'-prefixed keys, both inside a final
        # artifact's _stages dict and at an incremental artifact's top
        # level; bookkeeping keys (stages_failed, aborted, ...) must not
        # inflate the count
        stages = doc.get("_stages")
        pool = stages if isinstance(stages, dict) and stages else doc
        return sum(1 for k in pool
                   if str(k).startswith("_") and k != "_stages"
                   and isinstance(pool[k], dict))

    best = max(docs, key=lambda pd: (n_stages(pd[1]),
                                     pd[1].get("measured_at_utc") or ""))[1]
    best = dict(best, all_artifacts=[os.path.basename(p) for p in paths])
    return best


_GIT_HEAD_CACHE: dict = {}


def _git_head() -> str | None:
    """Short HEAD for artifact provenance, resolved once per repo per
    process — the code that produced a run's numbers is the checkout at
    start, even if a commit lands mid-run. Keyed by _REPO (the test seam
    monkeypatches it); a transient git failure is NOT cached, so a later
    write in the same run can still recover provenance."""
    if _REPO not in _GIT_HEAD_CACHE:
        try:
            head = subprocess.run(
                ["git", "-C", _REPO, "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
            ).stdout.strip() or None
        except Exception:
            head = None
        if head is None:
            return None
        _GIT_HEAD_CACHE[_REPO] = head
    return _GIT_HEAD_CACHE[_REPO]


def _write_measured_artifact(out: dict, stamp: str) -> str:
    """Persist the measurement-so-far as BENCH_MEASURED_<utc>.json with
    provenance (timestamp + git HEAD). Called after EVERY successful stage
    (same stamp → same file, progressively refined), so perf evidence
    survives a later stage's death (VERDICT r3 weak #1/#2).

    TINY dry-runs never persist: a CPU artifact with a numeric value would
    satisfy the watcher's measured-headline gate (disabling the real
    short-window path) and could be committed as if it were chip evidence."""
    if os.environ.get("FEDML_BENCH_TINY") == "1":
        return ""
    artifact = dict(out, measured_at_utc=stamp, git_head=_git_head())
    path = os.path.join(_REPO, f"BENCH_MEASURED_{stamp}.json")
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
    return path


# --- banked CPU baselines (VERDICT r4 weak #1) -------------------------------
# The torch-CPU comparison denominators need no chip, so they are measured
# tunnel-down and committed to git as BENCH_CPU_BASELINES.json. A live window
# then spends every second on chip stages and reuses the banked numbers.

def _cpu_baseline_path() -> str:
    # derived from _REPO at call time so the test seam (monkeypatched _REPO)
    # redirects it along with the measured artifacts
    return os.path.join(_REPO, "BENCH_CPU_BASELINES.json")


def _cpu_stage_env() -> dict:
    """Env for CPU-only stage subprocesses: drop the axon pool var (this
    image's sitecustomize force-selects the remote TPU backend, and with a
    stalled tunnel even jax import-time work hangs) and pin jax to cpu.
    The torch stages don't import jax, but the guard costs nothing."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _load_cpu_baselines() -> dict | None:
    try:
        with open(_cpu_baseline_path()) as f:
            return json.load(f)
    except Exception:
        return None


_CPU_BASELINE_STAGES = (("cpu_llm", "cpu_llm_tokens_per_sec", 400),
                        ("cpu_resnet", "cpu_resnet_images_per_sec", 200))


def _ensure_cpu_baselines(force: bool = False) -> dict | None:
    """Return the banked CPU baselines, measuring + writing whatever is
    missing first (all of it under ``force``). Runs entirely on the host —
    safe tunnel-down. A partial bank (one stage failed last time) is
    COMPLETED here, not returned as-is — otherwise one bad banking run
    would permanently null the missing denominator."""
    banked = (_load_cpu_baselines() or {}) if not force else {}
    missing = [(name, key, budget) for name, key, budget in _CPU_BASELINE_STAGES
               if banked.get(key) is None]
    if not missing:
        return banked
    stamp_now = datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    out: dict = {k: v for k, v in banked.items()
                 if k not in ("measured_at_utc", "git_head")}
    # preserved values keep their ORIGINAL stamp (per-key provenance): a
    # completion run must not re-claim an old measurement as its own
    for _name, key, _budget in _CPU_BASELINE_STAGES:
        if banked.get(key) is not None:
            out.setdefault(f"{key}_measured_at", banked.get(
                f"{key}_measured_at", banked.get("measured_at_utc")))
    for name, key, budget in missing:
        result, err = _spawn_stage(name, budget, env=_cpu_stage_env())
        if err is not None:
            print(f"warning: {err}", file=sys.stderr)
        else:
            out.update(result)
            if result.get(key) is not None:
                out[f"{key}_measured_at"] = stamp_now
    if not any(out.get(key) is not None for _, key, _ in _CPU_BASELINE_STAGES):
        return None
    artifact = dict(out, measured_at_utc=stamp_now, git_head=_git_head())
    with open(_cpu_baseline_path(), "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"banked CPU baselines -> {_cpu_baseline_path()}", file=sys.stderr)
    return artifact


# --- stage runners (each runs in its own subprocess) -------------------------

def _round_floats(d: dict, nd: int = 4) -> dict:
    return {k: (round(v, nd) if isinstance(v, float) else v) for k, v in d.items()}


def _retry_transient(fn, *args, **kw):
    """The remote tunnel occasionally drops a single request mid-compile
    ('response body closed'); one SAME-CONFIG retry rides out the flake so
    it is never misread as OOM (which would silently degrade the headline
    to remat). Integrity-guard failures stay fatal — a broken measurement
    must not get a second roll of the dice — and genuine OOM raises again
    identically on the retry, landing in the caller's fallback. The retry
    runs OUTSIDE the except block so the failed attempt's traceback (which
    pins its device buffers) is released first."""
    try:
        return fn(*args, **kw)
    except BenchIntegrityError:
        raise
    except Exception as e:
        print(f"warning: {getattr(fn, '__name__', fn)} failed ({e!r}); "
              "retrying same config once", file=sys.stderr)
        # RESOURCE_EXHAUSTED right at a stage's FIRST allocation is the
        # predecessor stage's HBM not yet reaped by the remote allocator
        # (r5 full ladder: llm_xla died at PRNGKey seconds after llm_pallas
        # exited, then its immediate retry died identically). Give the
        # remote side time to free before the one retry — but sleep OUTSIDE
        # this except block: the live traceback pins the failed attempt's
        # own device buffers, and those must be released BEFORE the wait or
        # an own-allocation OOM gets no reap time at all. The sleep also
        # fires for deterministic own-allocation OOMs, where it wastes
        # 45s + one doomed retry before the caller's fallback — accepted:
        # the cases aren't mechanically distinguishable here, the cost is
        # bounded, and the only downstream timing gate it can push past
        # (the bs=2x probe's 600s cutoff) guards a strictly additive probe.
        oom = "RESOURCE_EXHAUSTED" in repr(e) or "ResourceExhausted" in repr(e)
    if oom:
        print("note: resource-exhausted; sleeping 45s for the device "
              "allocator to reap freed buffers", file=sys.stderr)
        time.sleep(45)  # fedlint: disable=bare-sleep one-shot allocator-reap pause before the single OOM respawn, not a retry loop
    return fn(*args, **kw)


def _enable_compile_cache() -> None:
    """Persistent compilation cache for stage subprocesses: a SECOND tunnel
    window re-running the same stage hits cached executables instead of
    re-paying minutes of cold compile. One shared definition
    (fedml_tpu/utils/compile_cache.py) keeps bench stages and serving
    replicas on the SAME cache directory."""
    from fedml_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()


def _run_stage(name: str, trace=None) -> None:
    """Entry point for `python bench.py --stage NAME`: run ONE measurement in
    this process and print exactly one JSON line. The process exits afterward,
    releasing every device buffer it held — the orchestrator's isolation
    guarantee.

    ``trace`` (the --trace flag) wraps the stage in a ``bench.<name>``
    telemetry span and writes the Chrome-trace/Perfetto JSON to that path on
    the way out (open in ui.perfetto.dev). The overhead guard runs first:
    ``span()`` on a disabled registry must stay under 1µs/call — the measured
    number ships in the JSON (tier-1 pins the same bound) and a breach warns
    on stderr."""
    if name not in ("cpu_llm", "cpu_resnet"):
        # torch-only baseline stages stay jax-free (their budgets are tight
        # and they never compile jax code)
        _enable_compile_cache()
    if trace is None:
        out = _stage_result(name)
    else:
        from fedml_tpu.core import telemetry as tel  # stdlib-only import

        from fedml_tpu.core.telemetry import flight_recorder

        overhead_ns = tel.disabled_span_overhead_ns()
        if overhead_ns >= 1000.0:
            print(f"warning: disabled-path span costs {overhead_ns:.0f}ns/call "
                  "(budget < 1000ns)", file=sys.stderr)
        # same contract for the flight recorder: an enabled record() stays
        # under 2µs/call, and with no active recorder the module helpers are
        # a None-check (tier-1 pins both bounds)
        recorder_ns = flight_recorder.enabled_event_overhead_ns()
        if recorder_ns >= 2000.0:
            print(f"warning: enabled recorder event costs {recorder_ns:.0f}ns/call "
                  "(budget < 2000ns)", file=sys.stderr)
        recorder_noop_ns = flight_recorder.noop_event_overhead_ns()
        tel.set_enabled(True)
        tel.reset()
        with tel.span(f"bench.{name}"):
            out = _stage_result(name)
        # merge: multi-stage runs pointing --trace at ONE path accumulate
        # events instead of each stage clobbering the previous stage's spans
        out["trace_file"] = tel.export_chrome_trace(trace, merge=True)
        out["telemetry_disabled_span_ns"] = round(overhead_ns, 1)
        out["telemetry_recorder_event_ns"] = round(recorder_ns, 1)
        out["telemetry_recorder_noop_ns"] = round(recorder_noop_ns, 1)
        rec = flight_recorder.active()
        if rec is not None and rec.last_dump_path:
            # a stage that crash-dumped mid-measurement surfaces the path in
            # its JSON (bench_watch forwards it into the artifact log)
            out["crash_dump"] = rec.last_dump_path
    print(json.dumps(_round_floats(out)))


def _stage_result(name: str) -> dict:
    """Dispatch ONE stage measurement and return its result dict."""
    _STAGE_T0 = time.monotonic()
    if name == "llm_pallas":
        # headline: Pallas flash attention, NO remat — with the [T,T]-free
        # kernel the 268M proxy's activations fit HBM, and skipping recompute
        # is pure throughput; a memory-limited chip falls back to remat, and
        # a Mosaic-rejected kernel (ADVICE r3: the lane-1 block layout has
        # never met the real compiler) falls back to einsum attention —
        # a measured einsum headline beats a dead stage, and the JSON's
        # attention_impl field keeps the substitution visible.
        # FEDML_BENCH_FAST=1 (the --short-window path): fewer reps and no
        # bs=2x probe, sized to land a headline inside a ~3-minute window.
        fast = os.environ.get("FEDML_BENCH_FAST") == "1"
        reps = 4 if fast else 10
        try:
            out = _retry_transient(_bench_llm_tpu, reps=reps, remat=False)
            out["remat"] = False
        except BenchIntegrityError:
            raise
        except Exception as e:  # noqa: BLE001 - twice-reproduced: OOM-shaped
            print(f"warning: no-remat LLM bench failed ({e!r}); retrying with remat",
                  file=sys.stderr)
            try:
                out = _bench_llm_tpu(reps=reps, remat=True)
                out["remat"] = True
            except BenchIntegrityError:
                raise
            except Exception as e2:  # noqa: BLE001
                print(f"warning: pallas LLM bench failed under remat too ({e2!r}); "
                      "falling back to xla attention for the headline",
                      file=sys.stderr)
                out = _retry_transient(_bench_llm_tpu, reps=reps,
                                       attention_impl="xla", remat=True)
                out["remat"] = True
        # larger batches usually raise MFU (bigger matmuls per dispatch);
        # tunnel windows are rare, so try bs=2x in the SAME window and ship
        # whichever measured faster — both results stay in the output. Only
        # probe while well inside the stage budget (1500s): overrunning it
        # would killpg the stage and discard the SUCCESSFUL 1x headline
        if (not fast
                and out["attention_impl"] == "pallas"
                and out["shape"]["bs"] == _llm_shape()["bs"]
                and time.monotonic() - _STAGE_T0 < 600.0):
            try:
                out2 = _bench_llm_tpu(reps=6, remat=out["remat"],
                                      bs=2 * _llm_shape()["bs"])
                out2["remat"] = out["remat"]
                out["bs2x_tokens_per_sec"] = round(out2["tokens_per_sec"], 1)
                out["bs2x_mfu"] = round(out2["mfu"], 4)
                if out2["mfu"] > out["mfu"]:
                    out2["bs1x_tokens_per_sec"] = round(out["tokens_per_sec"], 1)
                    out2["bs1x_mfu"] = round(out["mfu"], 4)
                    out = out2
            except Exception as e3:  # noqa: BLE001 - the probe is strictly
                # additive: OOM, a transient flake, or even an integrity
                # failure taints only the PROBE measurement — the bs=1x
                # headline already passed its own guards and must ship
                print(f"note: bs=2x probe failed ({e3!r}); keeping bs=1x headline",
                      file=sys.stderr)
    elif name == "llm_xla":
        # remat is the PRIMARY config here: the einsum path materializes
        # [T,T] score tensors fwd AND saved-for-bwd (~256MB/layer at the
        # headline geometry), which deterministically OOMed a 16GB v5e at
        # warmup (measured 2026-08-01) — and the failed attempt's buffers
        # then starved every later attempt in the same process, including
        # the remat fallback that fits. The flash/pallas headline runs the
        # same geometry WITHOUT remat; that asymmetry is part of the result
        # (recorded via the remat field) — flash attention's whole point is
        # not materializing scores.
        # FEDML_LLM_XLA_BS: set by the orchestrator's one-shot OOM respawn
        # (below) — a RESOURCE_EXHAUSTED death even WITH remat means this
        # chip can't fit the headline geometry on the einsum path, and the
        # failed attempt's buffers starve every in-process retry, so the
        # recovery MUST be a fresh subprocess at smaller batch
        xla_bs = os.environ.get("FEDML_LLM_XLA_BS")
        xla_kw = {"bs": int(xla_bs)} if xla_bs else {}
        if os.environ.get("FEDML_LLM_XLA_SHARDED") == "1":
            # orchestrator OOM-respawn step 1: shard params/grads/opt state
            # over every local device BEFORE any geometry degradation. On a
            # single-device host sharding cannot change the memory picture;
            # fail fast with a marker the orchestrator can distinguish from
            # a second OOM so it moves straight to the half-batch respawn.
            import jax

            if jax.device_count() < 2:
                raise RuntimeError(
                    "SHARDED_UNAVAILABLE: 1 device — the fsdp-sharded train "
                    "state needs a multi-device mesh")
            xla_kw["fsdp_shard"] = True
        out = _retry_transient(_bench_llm_tpu, reps=6, attention_impl="xla",
                               remat=True, **xla_kw)
        out["remat"] = True
        if xla_bs:
            out["degraded_bs"] = int(xla_bs)
        # record the measured OOM fact only for the geometry AND device it
        # was actually observed at — a tiny dry-run, a future flagship-shape
        # change, or a bigger-HBM chip must not emit an artifact asserting a
        # measurement this run never made
        if (out.get("shape", {}).get("bs") == _LLM_SHAPE["bs"]
                and out.get("shape", {}).get("seq") == _LLM_SHAPE["seq"]
                and "v5 lite" in str(out.get("device", ""))):
            out["no_remat_oom"] = ("einsum attention at bs8/seq1024 OOMs "
                                   "16GB v5e without remat (measured 2026-08-01)")
    elif name == "decode":
        out = _retry_transient(_bench_llm_decode_tpu)
    elif name == "decode_int8":
        out = _retry_transient(_bench_llm_decode_tpu, weight_quant="int8")
    elif name == "resnet":
        out = _retry_transient(_bench_resnet_tpu)
    elif name == "attn_micro":
        out = _retry_transient(_bench_attn_micro)
    elif name == "agg":
        out = _retry_transient(_bench_agg)
    elif name == "agg_sharded":
        out = _retry_transient(_bench_agg_sharded)
    elif name == "async_rounds":
        out = _retry_transient(_bench_async_rounds)
    elif name == "fleet_scale":
        out = _retry_transient(_bench_fleet_scale)
    elif name == "wan_profile":
        out = _retry_transient(_bench_wan_profile)
    elif name == "pipeline_overlap":
        out = _retry_transient(_bench_pipeline_overlap)
    elif name == "slo_overhead":
        out = _bench_slo_overhead()
    elif name == "devperf_overhead":
        out = _bench_devperf_overhead()
    elif name == "modelwatch_overhead":
        out = _bench_modelwatch_overhead()
    elif name == "secagg_overhead":
        out = _bench_secagg_overhead()
    elif name == "placement_search":
        out = _retry_transient(_bench_placement_search)
    elif name == "llm_pallas_tuned":
        # re-run the pallas headline under the block config attn_micro just
        # recorded (the orchestrator exports FEDML_FLASH_BLOCK_Q/K into this
        # stage's env from the verdict file) — without this, a tuned config
        # only pays off in the NEXT window. Skips itself when there is no
        # non-default verdict to apply.
        bq = os.environ.get("FEDML_FLASH_BLOCK_Q")
        bk = os.environ.get("FEDML_FLASH_BLOCK_K")
        if not bq or not bk or (bq, bk) == ("128", "128"):
            out = {"skipped": "no non-default flash_blocks verdict"}
        else:
            out = _retry_transient(_bench_llm_tpu, reps=10,
                                   attention_impl="pallas", remat=False)
            out["remat"] = False
    elif name == "memplan":
        out = _bench_memplan()
    elif name == "cpu_llm":
        out = {"cpu_llm_tokens_per_sec": _bench_llm_torch_cpu(_LLM_SHAPE)}
    elif name == "cpu_resnet":
        out = {"cpu_resnet_images_per_sec": _bench_resnet_torch_cpu()}
    elif name == "serving":
        out = _bench_llm_serving()
    elif name == "serving_load":
        out = _bench_llm_serving_load()
    else:
        raise SystemExit(f"unknown stage {name!r}")
    return out


# (stage, per-stage wall budget seconds). Headline FIRST; serving LAST so its
# replica children can never leave a chip half-full under a later stage.
_STAGES: list[tuple[str, int]] = [
    ("llm_pallas", 1500),
    ("llm_xla", 1200),
    ("decode", 900),
    # int8 weight-only decode: the measured side of the serving/quant.py
    # story. Full decode budget — each stage is a FRESH subprocess and the
    # int8 kernels are a DIFFERENT program from fp decode's, so the only
    # cross-stage reuse is whatever the persistent compile cache
    # (_enable_compile_cache) can serve; budget for fully cold
    ("decode_int8", 900),
    ("resnet", 900),
    # bucketed-aggregation engine: clients/sec + effective HBM GB/s across
    # cohort sizes on the ResNet-56 and LLM pytrees (single-compile proof
    # rides along via agg_accum_traces)
    ("agg", 600),
    # mesh-parallel server round vs the single-device engine on the same
    # cohort: per-device HBM ratio (<=60% integrity guard), parity, and
    # ingestion-overlap efficiency; single-chip windows respawn it on the
    # virtual 8-CPU mesh (orchestrator, below)
    ("agg_sharded", 600),
    # async buffered federation: rounds/hr at 1k/10k/100k simulated clients
    # (flatness + bit-exact sync parity + zero-retrace integrity guards)
    ("async_rounds", 600),
    # sketch-based fleet telemetry at 1M simulated clients: root-view
    # quantiles within 2% of numpy exact, edge-merged == flat-merged,
    # memory O(sketch-bytes x nodes), ingest+merge < 1% of the stage wall
    # (all integrity-guarded)
    ("fleet_scale", 600),
    # per-link WAN observability: heterogeneous chaos-throttle fleet, the
    # netlink estimators must recover every injected bandwidth within 20%
    # with probe overhead < 1% of the window (both integrity-guarded). The
    # window itself is seconds; the budget covers interpreter start + retry
    ("wan_profile", 240),
    # pipelined round execution: per-client train/compress/uplink streamed
    # through the stage executor over a throttled broker link; the measured
    # overlap fraction (>= 0.5) and the pipelined-vs-serial speedup (> 1x)
    # are both integrity-guarded. Sub-minute of actual work; budget covers
    # interpreter start + retry
    ("pipeline_overlap", 240),
    # SLO evaluator overhead: simulated round loop through a real activated
    # engine + deliberately-breaching canary spec; tsdb ingest + burn-rate
    # ticks must stay under 1% of loop wall (integrity-guarded). Pure
    # CPU/numpy — seconds of work; the budget covers interpreter start
    ("slo_overhead", 180),
    # modelwatch fold-boundary stats overhead: plain vs watched bucketed
    # fold inside a round-shaped loop; watched-vs-plain round wall delta
    # < 1%, zero added recompiles, bit-exact parity, and injected
    # NaN/scaled clients must be caught (all integrity-guarded)
    ("modelwatch_overhead", 240),
    # windowed SecAgg + accounted-DP fold overhead: masked+noised vs plain
    # round walls in a round-shaped loop; masked-vs-plain delta < 5%,
    # zero-dropout unmask bit-exact vs the honest quantized fold, mask-off
    # path bit-identical, accountant stepped per noised publish (all
    # integrity-guarded). Host-side numpy + one fused kernel — seconds of
    # work; the budget covers interpreter start + retry
    ("secagg_overhead", 240),
    # devperf registry overhead + live-vs-analytic MFU parity: a real
    # (tiny-aware) instrumented llama step loop; registry MFU must match
    # bench's _mfu_from_rate within 15% and the registry's self-accounted
    # cost must stay under 1% of loop wall (both integrity-guarded)
    ("devperf_overhead", 240),
    # auto-placement search: cost-model-seeded probes over (strategy x
    # publish_k x staleness exponent) on two workloads; default-vs-searched
    # speedup + the winning PlacementPlan JSON artifact (zero-retrace +
    # must-beat-baseline integrity guards)
    ("placement_search", 600),
    # attention-kernel block sweep: records the fastest config to
    # .bench_runtime/flash_blocks (6 small compiles + marginal timings) ...
    ("attn_micro", 600),
    # ... and the tuned headline re-run applies it IN THIS WINDOW (skips
    # itself when the verdict is absent or the 128x128 default)
    ("llm_pallas_tuned", 900),
    # real-HBM validation of the 7B plan: metadata math + one stats read,
    # plus (no-bytes_limit devices) one plan_bytes allocation on chip
    ("memplan", 480),
    ("cpu_llm", 400),
    ("cpu_resnet", 200),
    # must exceed the stage's own internal worst case: 2x300s serial replica
    # startup + 300s ready-wait + 2x240s warm + measured requests
    ("serving", 1800),
    # 1k-stream continuous-batching load test: in-process engine, so the
    # worst case is warmup compiles + 1024 B=1 prefill admissions + chunked
    # decode of ~32k tokens; runs after `serving` for the same
    # chip-occupancy reason
    ("serving_load", 1200),
]


_CURRENT_STAGE_PROC: subprocess.Popen | None = None


def _kill_stage_group(proc: subprocess.Popen) -> None:
    """SIGKILL the stage's whole process GROUP: a serving stage's replica
    grandchildren hold HBM, and killing only the stage process would leave
    them alive on the chip — exactly the r03 failure mode. Stages are
    spawned with start_new_session=True, and their own children (replicas)
    inherit that group, so one killpg reaps the whole tree."""
    import signal

    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        if proc.poll() is None:
            proc.kill()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        pass


def _handle_term(signum, frame):  # noqa: ARG001
    """bench_watch's outer `timeout` (and the driver) signal only THIS
    orchestrator; forward the death to the in-flight stage's process group
    so no replica grandchild outlives the bench holding HBM."""
    if _CURRENT_STAGE_PROC is not None:
        _kill_stage_group(_CURRENT_STAGE_PROC)
    sys.exit(128 + signum)


def _spawn_stage(name: str, budget_s: int, argv: list[str] | None = None,
                 env: dict | None = None) -> tuple[dict | None, str | None]:
    """Run one stage subprocess; returns (parsed_json, None) or
    (None, "stage: failure summary"). Output goes through temp files, not
    PIPE, so a timeout kill still leaves the partial stderr readable for
    the failure record. ``argv`` overrides the stage command (test seam for
    the kill-the-whole-tree contract); ``env`` overrides the child env
    (CPU-only stages must never touch the tunnel)."""
    global _CURRENT_STAGE_PROC
    import tempfile

    t0 = time.perf_counter()
    with tempfile.TemporaryFile(mode="w+") as f_out, \
         tempfile.TemporaryFile(mode="w+") as f_err:
        proc = subprocess.Popen(
            argv or [sys.executable, os.path.abspath(__file__), "--stage", name],
            stdout=f_out, stderr=f_err, text=True, cwd=_REPO, env=env,
            start_new_session=True,  # one killpg reaps replica grandchildren
        )
        _CURRENT_STAGE_PROC = proc
        timed_out = False
        try:
            proc.wait(timeout=budget_s)
        except subprocess.TimeoutExpired:
            timed_out = True
            _kill_stage_group(proc)
        finally:
            _CURRENT_STAGE_PROC = None
        f_out.seek(0)
        f_err.seek(0)
        stdout, stderr = f_out.read(), f_err.read()
    dt = time.perf_counter() - t0
    for line in stderr.splitlines():
        print(f"[{name}] {line}", file=sys.stderr)
    if timed_out:
        tail = stderr.strip().splitlines()
        where = tail[-1][:200] if tail else "no output"
        return None, f"{name}: timeout after {budget_s}s (last stderr: {where})"
    if proc.returncode != 0:
        # summarize the failure class (RESOURCE_EXHAUSTED etc.) from the tail
        tail = (stderr or stdout).strip().splitlines()
        summary = next(
            (ln.strip() for ln in reversed(tail)
             if any(t in ln for t in ("Error", "RESOURCE_EXHAUSTED", "Exception", "error:"))),
            tail[-1] if tail else "no output",
        )
        return None, f"{name}: rc={proc.returncode} {summary[:300]}"
    last = stdout.strip().splitlines()
    if not last:
        return None, f"{name}: rc=0 but no JSON line"
    try:
        parsed = json.loads(last[-1])
    except json.JSONDecodeError:
        return None, f"{name}: unparseable stage output {last[-1][:200]!r}"
    print(f"[{name}] done in {dt:.0f}s", file=sys.stderr)
    return parsed, None


# Lock/pidfile live in a 0700 dir under the repo, not world-writable /tmp:
# a squatted /tmp pidfile (or a symlinked lock path — open(..., "a+") follows
# symlinks) could point the preempt path at an unrelated same-user process
# (ADVICE r4). tools/bench_watch.sh flocks the same path.
_BENCH_RUNTIME_DIR = os.path.join(_REPO, ".bench_runtime")
_BENCH_LOCK_PATH = os.path.join(_BENCH_RUNTIME_DIR, "bench.lock")
_BENCH_PID_PATH = os.path.join(_BENCH_RUNTIME_DIR, "bench.pid")


def _pid_is_bench(pid: int) -> bool:
    """True iff ``pid``'s cmdline references this bench script — the preempt
    SIGTERM must never land on a process that merely inherited a stale or
    squatted pidfile (ADVICE r4)."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read().replace(b"\0", b" ").decode(errors="replace")
    except OSError:
        return False
    return "bench.py" in cmdline


def _kernel_hash() -> str | None:
    import hashlib

    path = os.path.join(_REPO, "fedml_tpu", "ops", "flash_attention.py")
    try:
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return None


def _flash_mode_env() -> dict | None:
    """Honor the smoke's verdict on the flash-kernel stats layout
    (tools/tpu_smoke_flash.py writes '.bench_runtime/flash_stats_mode' as
    '<mode> <kernel sha256>'): 'wide' means real Mosaic rejected the
    default (block_q, 1) layout but accepted the 128-lane-broadcast one, so
    chip stages must run wide or the headline silently degrades to the
    xla-einsum fallback. A verdict rendered on DIFFERENT kernel code (hash
    mismatch) is ignored — it says nothing about the current kernels."""
    try:
        with open(os.path.join(_BENCH_RUNTIME_DIR, "flash_stats_mode")) as f:
            parts = f.read().strip().split()
    except OSError:
        return None
    mode = parts[0] if parts else ""
    verdict_hash = parts[1] if len(parts) > 1 else None
    if mode != "wide":
        return None
    if verdict_hash is not None and verdict_hash != _kernel_hash():
        print("warning: flash_stats_mode verdict is for a different kernel "
              "hash; ignoring it", file=sys.stderr)
        return None
    env = dict(os.environ)
    env["FEDML_FLASH_WIDE_STATS"] = "1"
    return env


def _flash_blocks_env(env: dict | None) -> dict | None:
    """Honor the attention microbench's recorded block-size verdict
    (.bench_runtime/flash_blocks, '<bq> <bk> <kernel sha256>') by exporting
    FEDML_FLASH_BLOCK_Q/K into the stage env. Hash-mismatched verdicts are
    ignored — they tuned different kernel code."""
    try:
        with open(os.path.join(_BENCH_RUNTIME_DIR, "flash_blocks")) as f:
            parts = f.read().strip().split()
    except OSError:
        return env
    if len(parts) != 3 or parts[2] != _kernel_hash():
        return env
    env = dict(env if env is not None else os.environ)
    env["FEDML_FLASH_BLOCK_Q"], env["FEDML_FLASH_BLOCK_K"] = parts[0], parts[1]
    return env


def _acquire_bench_lock(watcher: bool, preempt_wait_s: float = 120.0):
    """ONE bench owns the chip at a time. The opportunistic watcher
    (tools/bench_watch.sh, FEDML_BENCH_WATCHER=1) yields: if another bench
    holds the lock it returns None and the caller emits a structured skip.
    A DRIVER run preempts: it SIGTERMs the holder (whose _handle_term kills
    the in-flight stage group and exits, releasing the flock with it) and
    waits for the lock — without this, the driver's end-of-round capture
    can land mid-watcher-bench and the two runs OOM each other on one chip.
    Returns the open locked file (held for the process lifetime)."""
    import fcntl

    os.makedirs(_BENCH_RUNTIME_DIR, mode=0o700, exist_ok=True)
    f = open(_BENCH_LOCK_PATH, "a+")
    locked = True
    try:
        fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except (BlockingIOError, OSError):
        locked = False
        if watcher:
            f.close()
            return None
        try:
            with open(_BENCH_PID_PATH) as pf:
                holder = int(pf.read().strip())
            if _pid_is_bench(holder):
                print(f"warning: preempting bench pid {holder} (driver run "
                      "takes the chip)", file=sys.stderr)
                os.kill(holder, 15)  # SIGTERM -> holder reaps its stage, exits
            else:
                print(f"warning: pidfile names pid {holder} but its cmdline "
                      "is not a bench.py run; not killing it", file=sys.stderr)
        except (OSError, ValueError):
            pass
        deadline = time.monotonic() + preempt_wait_s
        while time.monotonic() < deadline:
            try:
                fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
                locked = True
                break
            except (BlockingIOError, OSError):
                time.sleep(1.0)  # fedlint: disable=bare-sleep bench-lock acquisition poll against the preempted holder, not a retry
        else:
            # holder would not die; proceed anyway rather than skip the
            # driver's only capture of the round (worst case matches the
            # old behavior). The pidfile is left ALONE: it still accurately
            # names the live flock holder (tombstoning it would strand every
            # later driver with nobody to preempt while the healthy holder
            # keeps the chip), and the _pid_is_bench cmdline guard already
            # covers the pid-recycled/squatted case ADVICE r4 raised. The
            # unlocked state is flagged for the emitted JSON so a double-run
            # window is visible in artifacts.
            print("warning: bench lock still held after preempt wait; "
                  "proceeding unlocked", file=sys.stderr)
            global _PROCEEDED_UNLOCKED
            _PROCEEDED_UNLOCKED = True
    if locked:
        # the pidfile names the LOCK HOLDER only: writing it on the
        # proceed-unlocked path would point later preemptors at a process
        # that never held the lock (and leave the real holder running)
        with open(_BENCH_PID_PATH, "w") as pf:
            pf.write(str(os.getpid()))
    return f


_PROCEEDED_UNLOCKED = False


def main() -> None:
    import signal

    signal.signal(signal.SIGTERM, _handle_term)
    signal.signal(signal.SIGINT, _handle_term)
    watcher = os.environ.get("FEDML_BENCH_WATCHER") == "1"
    lock = _acquire_bench_lock(watcher)
    if watcher and lock is None:
        print(json.dumps({
            "skipped": "bench_lock_held",
            "detail": "another bench run owns the chip; the watcher yields",
            "last_measured": _last_measured(),
        }))
        sys.exit(1)
    try:
        _probe_backend()
    except BenchProbeTimeout as e:
        # Structured skip record (VERDICT r2 weak #7): the driver/judge can
        # mechanically tell "tunnel down, code fine" from "bench crashed",
        # and the last committed measurement rides along for reference.
        # The CPU comparison denominators need no chip — measure and bank
        # them NOW (VERDICT r4 weak #1: the old path discarded them) so a
        # short future window spends every second on chip stages.
        cpu_banked = _ensure_cpu_baselines()
        print(json.dumps({
            "skipped": "tunnel_stalled",
            "probe_timeout_s": 180,
            "detail": str(e),
            "cpu_baselines": cpu_banked,
            "last_measured": _last_measured(),
        }))
        sys.exit(1)

    stamp = datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    stage_out: dict[str, dict] = {}
    failed: list[str] = []
    merged: dict = {"stages_failed": failed}
    if _PROCEEDED_UNLOCKED:
        merged["bench_lock"] = "proceeded_unlocked"
    remaining = list(_STAGES)
    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    # tiny dry-runs never touch the flagship CPU denominators: the ratio of
    # tiny-geometry throughput over a flagship baseline is meaningless
    # (main_short applies the same guard)
    banked = None if tiny else _load_cpu_baselines()
    if banked is not None:
        # chip windows are scarce: reuse the committed host-side denominators
        # instead of burning window time re-measuring them. Only a stage
        # whose banked value actually EXISTS is skipped — a partial banking
        # (one cpu stage failed) must not permanently suppress the other
        skip = []
        for stage, key, _budget in _CPU_BASELINE_STAGES:
            if banked.get(key) is not None:
                skip.append(stage)
                # per-key stamp when present (a completed partial bank
                # carries one per value); file-level stamp otherwise
                stage_out[stage] = {
                    key: banked[key],
                    "source": ("banked " + str(banked.get(
                        f"{key}_measured_at", banked.get("measured_at_utc"))))}
        remaining = [(n, b) for n, b in remaining if n not in skip]
        banked_stages = skip
    flash_env = _flash_mode_env()
    while remaining:
        stage_name, budget = remaining.pop(0)
        env = dict(flash_env) if flash_env is not None else None
        env = _flash_blocks_env(env)
        if stage_name == "llm_pallas_tuned":
            # spawn only when the re-run would measure something NEW: a
            # pallas no-remat flagship headline exists AND the current
            # verdict resolves to a block config the headline did not
            # already run (in steady state llm_pallas itself runs under the
            # persisted verdict, making this stage redundant)
            head = stage_out.get("llm_pallas") or {}
            verdict = (env or {}).get("FEDML_FLASH_BLOCK_Q"), (env or {}).get(
                "FEDML_FLASH_BLOCK_K")
            verdict_blocks = (f"{verdict[0]}x{verdict[1]}"
                              if all(verdict) else None)
            if (head.get("attention_impl") != "pallas" or head.get("remat")
                    or head.get("shape", {}).get("bs") != _llm_shape()["bs"]
                    or verdict_blocks is None
                    or verdict_blocks == head.get("flash_blocks")):
                stage_out[stage_name] = {
                    "skipped": "headline already ran this config (or is not "
                               "a no-remat pallas flagship run)"}
                continue
        if stage_name in ("memplan", "agg_sharded"):
            # memplan's plan math — and agg_sharded's server mesh — run on a
            # virtual 8-device CPU mesh alongside the real chip (for memplan
            # it is metadata only; agg_sharded actually computes there when
            # the default platform is multi-device CPU)
            env = env or dict(os.environ)
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                " --xla_force_host_platform_device_count=8").strip()
        result, err = _spawn_stage(stage_name, budget, env=env)
        if (err is None and stage_name == "agg_sharded"
                and isinstance(result, dict)
                and "single-device" in str(result.get("skipped", ""))):
            # single-chip accelerator window: the sharded engine cannot lay
            # out over one device, but the layout/overlap/parity measurement
            # is platform-independent — respawn once on the virtual 8-CPU
            # mesh; the artifact's device field keeps the substitution
            # visible (throughput there is a CPU number, never compared
            # against chip stages)
            retry_env = dict(env)
            retry_env["JAX_PLATFORMS"] = "cpu"
            retry_env.pop("PALLAS_AXON_POOL_IPS", None)
            print("note: agg_sharded found a single-device chip; respawning "
                  "on the virtual 8-CPU mesh", file=sys.stderr)
            result2, err2 = _spawn_stage(stage_name, budget, env=retry_env)
            if err2 is None:
                result = dict(result2)
                result["agg_sharded_platform"] = "cpu_virtual_8dev"
            else:
                print(f"warning: {err2}", file=sys.stderr)
        if (err is not None and stage_name == "llm_xla"
                and ("RESOURCE_EXHAUSTED" in err or "ResourceExhausted" in err)):
            # r5 -> r7: llm_xla died RESOURCE_EXHAUSTED even with remat on —
            # the chip can't fit the einsum path at the headline batch, and
            # the dead attempt's buffers starve every in-process retry, so
            # every recovery is a FRESH subprocess. Recovery 1 (r7): shard
            # the train state over every local device (ZeRO-3 layout — the
            # measured geometry is unchanged, so it gets first claim on the
            # respawn). Recovery 2 (the r5 path, now the fallback): half
            # batch — the shrunken geometry ships honestly via degraded_bs
            # (and the shape guard on no_remat_oom keeps the full-geometry
            # OOM note from being asserted by a degraded run).
            print(f"warning: {err}", file=sys.stderr)
            retry_env = dict(env if env is not None else os.environ)
            retry_env["FEDML_LLM_XLA_SHARDED"] = "1"
            print("note: llm_xla OOMed at headline bs; respawning once with "
                  "the fsdp-sharded train state", file=sys.stderr)
            result, err = _spawn_stage(stage_name, budget, env=retry_env)
            sharded_ran = not (err is not None and "SHARDED_UNAVAILABLE" in err)
            if err is None:
                result = dict(result)
                result["sharded_attempted"] = True
            elif ("RESOURCE_EXHAUSTED" in err or "ResourceExhausted" in err
                    or not sharded_ran):
                small = max(1, int(_llm_shape()["bs"]) // 2)
                retry_env2 = dict(env if env is not None else os.environ)
                if sharded_ran:
                    # sharding ran but the chip still OOMed: keep it for the
                    # half-batch attempt (strictly more headroom)
                    retry_env2["FEDML_LLM_XLA_SHARDED"] = "1"
                retry_env2["FEDML_LLM_XLA_BS"] = str(small)
                print(f"warning: {err}", file=sys.stderr)
                print(f"note: sharded respawn did not recover; respawning "
                      f"once at bs={small}", file=sys.stderr)
                result, err = _spawn_stage(stage_name, budget, env=retry_env2)
                if err is None:
                    result = dict(result)
                    result["sharded_attempted"] = (True if sharded_ran
                                                   else "unavailable")
        if err is not None:
            print(f"warning: {err}", file=sys.stderr)
            failed.append(err)
            # exact budget-exhaustion format from _spawn_stage — a crash
            # whose stderr merely CONTAINS 'timeout' must not trigger this
            if err.startswith(f"{stage_name}: timeout after"):
                # a stage timeout is the signature of a mid-run tunnel stall;
                # re-probe cheaply — if the tunnel is gone, burning every
                # remaining chip stage's full budget (hours) measures nothing
                # and keeps the watcher from re-probing for the next window
                try:
                    _probe_backend(timeout_s=90)
                except BenchProbeTimeout:
                    chip_stages = [(n, b) for n, b in remaining
                                   if n not in ("cpu_llm", "cpu_resnet")]
                    skipped = [n for n, _ in chip_stages]
                    print(f"warning: tunnel stalled mid-run; skipping "
                          f"chip stages {skipped}", file=sys.stderr)
                    failed.extend(f"{n}: skipped (tunnel stalled mid-run)"
                                  for n in skipped)
                    merged["aborted"] = "tunnel_stalled_midrun"
                    # the torch-CPU baselines never touch the tunnel — they
                    # still measure (vs_baseline survives the stall)
                    remaining = [(n, b) for n, b in remaining
                                 if n in ("cpu_llm", "cpu_resnet")]
            continue
        stage_out[stage_name] = result
        merged.update({f"_{stage_name}": result})
        _write_measured_artifact(merged, stamp)  # incremental: survives later deaths

    llm = stage_out.get("llm_pallas")
    llm_xla = stage_out.get("llm_xla")
    if llm is None and llm_xla is not None:
        # The pallas stage's in-process fallback ladder handles exceptions,
        # but a HANG (e.g. a Mosaic compile that never returns over the
        # tunnel) ends in killpg — no ladder runs. Promote the measured xla
        # stage to the headline rather than shipping value:null next to a
        # perfectly good number; attention_impl="xla" keeps it honest.
        print("warning: llm_pallas stage produced nothing; promoting llm_xla "
              "measurement to the headline", file=sys.stderr)
        llm = llm_xla
    tuned = stage_out.get("llm_pallas_tuned")
    if (tuned is not None and tuned.get("tokens_per_sec") is not None
            and llm is not None and llm.get("attention_impl") == "pallas"
            # config parity: a tuned run may only claim a blocks-delta over
            # a headline with the same remat mode, batch size, and a
            # DIFFERENT block config — anything else attributes a remat/bs
            # effect to tuning
            and tuned.get("remat") == llm.get("remat")
            and tuned.get("shape", {}).get("bs") == llm.get("shape", {}).get("bs")
            and tuned.get("flash_blocks") != llm.get("flash_blocks")
            and tuned["tokens_per_sec"] > llm["tokens_per_sec"]):
        # the block-tuned re-run beat the default-config headline: promote
        # it, keeping the default run's numbers as provenance
        tuned = dict(tuned)
        tuned["default_blocks_tokens_per_sec"] = round(llm["tokens_per_sec"], 1)
        tuned["default_blocks_mfu"] = round(llm["mfu"], 4)
        llm = tuned
    decode = stage_out.get("decode")
    resnet = stage_out.get("resnet")
    serving = stage_out.get("serving") or {"endpoint_decode_tokens_per_sec": None}
    cpu_llm = (stage_out.get("cpu_llm") or {}).get("cpu_llm_tokens_per_sec")
    cpu_resnet = (stage_out.get("cpu_resnet") or {}).get("cpu_resnet_images_per_sec")

    out: dict = {"metric": "llm_train_tokens_per_sec", "stages_failed": failed}
    if tiny:
        # cpu stages still run at FLAGSHIP geometry in a tiny ladder, so
        # every tiny/flagship ratio below must be suppressed, not just the
        # artifact write
        out["tiny_dryrun"] = True
        cpu_llm = cpu_resnet = None
    if _PROCEEDED_UNLOCKED:
        # a double-run window existed (lock holder would not die); make it
        # visible in the artifact rather than only in stderr (ADVICE r4)
        out["bench_lock"] = "proceeded_unlocked"
    if banked is not None and banked_stages:
        # provenance names exactly the stages whose denominators were reused
        # — a partial bank live-measures the rest, and claiming "banked" for
        # a just-measured value would misattribute it
        out["cpu_baseline_source"] = (
            f"banked {banked.get('measured_at_utc')} ({', '.join(banked_stages)})")
    if llm is not None:
        out.update({
            "value": round(llm["tokens_per_sec"], 1),
            "unit": f"tokens/s (llama-{llm['n_params'] / 1e6:.0f}M full train step, bf16, "
                    f"seq{llm['shape']['seq']} bs{llm['shape']['bs']}, 1x {llm['device']})",
            "vs_baseline": round(llm["tokens_per_sec"] / cpu_llm, 2) if cpu_llm else None,
            "mfu": round(llm["mfu"], 4),
            "attention_impl": llm["attention_impl"],
            "remat": llm["remat"],
        })
        if llm.get("flash_blocks"):
            out["flash_blocks"] = llm["flash_blocks"]
        if llm.get("default_blocks_tokens_per_sec") is not None:
            out["default_blocks_tokens_per_sec"] = llm["default_blocks_tokens_per_sec"]
            out["default_blocks_mfu"] = llm["default_blocks_mfu"]
    else:
        out.update({"value": None, "unit": "tokens/s", "vs_baseline": None, "mfu": None})
    if llm_xla is not None:
        out["mfu_xla_attention"] = round(llm_xla["mfu"], 4)
        out["tokens_per_sec_xla_attention"] = round(llm_xla["tokens_per_sec"], 1)
        # the xla stage falls back to remat independently of the headline;
        # surface its mode so a mixed-remat comparison is visible in the
        # one-line JSON, not just the nested artifact
        out["remat_xla_attention"] = llm_xla["remat"]
        if llm_xla.get("degraded_bs") is not None:
            # the OOM-respawn path shrank the geometry — a reader comparing
            # xla vs pallas tokens/s must see the batch mismatch up front
            out["llm_xla_degraded_bs"] = llm_xla["degraded_bs"]
        if llm_xla.get("sharded_attempted") is not None:
            # the r7 recovery ladder ran: True = the fsdp-sharded respawn
            # executed (and produced this measurement unless degraded_bs is
            # also set); "unavailable" = single device, sharding impossible
            out["llm_xla_sharded_attempted"] = llm_xla["sharded_attempted"]
        if llm_xla.get("server_sharded"):
            out["llm_xla_mesh_devices"] = llm_xla.get("mesh_devices")
    if resnet is not None:
        out["resnet56_steps_per_sec"] = round(resnet["steps_per_sec"], 2)
        out["resnet56_mfu"] = round(resnet["mfu"], 4)
        if "fedavg_rounds_per_hr" in resnet:
            # the north-star vocabulary (BASELINE.md acceptance): FedAvg
            # rounds/hr on the ResNet-56/CIFAR client workload
            out["fedavg_rounds_per_hr"] = round(resnet["fedavg_rounds_per_hr"], 1)
            out["fedavg_round_shape"] = (
                f"{resnet['fedavg_clients']} clients x "
                f"{resnet['fedavg_local_steps']} steps x bs{resnet['bs']}")
        if "fedavg16_rounds_per_hr" in resnet:
            # the BASELINE acceptance cohort size (16 silos)
            out["fedavg16_rounds_per_hr"] = round(
                resnet["fedavg16_rounds_per_hr"], 1)
        if cpu_resnet:
            out["resnet56_vs_torch_cpu"] = round(
                resnet["steps_per_sec"] * resnet["bs"] / cpu_resnet, 2)
    if decode is not None:
        out["decode_tokens_per_sec"] = round(decode["decode_tokens_per_sec"], 1)
        if decode.get("decode_tokens_per_sec_long") is not None:
            out["decode_tokens_per_sec_long"] = round(
                decode["decode_tokens_per_sec_long"], 1)
            out["decode_new_long"] = decode["new_long"]
    decode_int8 = stage_out.get("decode_int8")
    if decode_int8 is not None:
        out["decode_tokens_per_sec_int8"] = round(
            decode_int8["decode_tokens_per_sec"], 1)
        if decode is not None and decode["decode_tokens_per_sec"] > 0:
            out["int8_decode_speedup"] = round(
                decode_int8["decode_tokens_per_sec"] / decode["decode_tokens_per_sec"], 2)
        if decode_int8.get("decode_tokens_per_sec_long") is not None:
            # the measured int8 long rate publishes unconditionally, like
            # its short counterpart; only the RATIO needs the fp denominator
            out["decode_tokens_per_sec_int8_long"] = round(
                decode_int8["decode_tokens_per_sec_long"], 1)
            # the length field must accompany the rate even when the fp
            # stage (the usual emitter of decode_new_long) died
            out.setdefault("decode_new_long", decode_int8["new_long"])
            if decode is not None and decode.get("decode_tokens_per_sec_long"):
                # the bandwidth-story comparison: long decode amortizes the
                # fixed per-call costs that mask int8 at new=128
                out["int8_decode_speedup_long"] = round(
                    decode_int8["decode_tokens_per_sec_long"]
                    / decode["decode_tokens_per_sec_long"], 2)
    out.update({k: (round(v, 1) if isinstance(v, float) else v)
                for k, v in serving.items()})
    serving_load = stage_out.get("serving_load")
    if serving_load is not None:
        out.update(serving_load)
        if decode is not None and serving_load.get("serving_load_tokens_per_sec"):
            # ISSUE 6 acceptance: endpoint decode within 10x of raw
            # single-chip decode — this is the ratio under test (>1 means
            # the endpoint is SLOWER than raw decode by that factor)
            out["serving_load_vs_decode"] = round(
                decode["decode_tokens_per_sec"]
                / serving_load["serving_load_tokens_per_sec"], 2)
    memplan = stage_out.get("memplan")
    if memplan is not None:
        # VERDICT r4 next #6: memory_plan_validated + the measured ceiling
        # (tri-state: None = NO measurement basis — neither a bytes_limit
        # nor the direct allocation probe; memplan_detail names the basis)
        out["memory_plan_validated"] = memplan["memory_plan_validated"]
        out["memplan_bytes_per_device"] = memplan["plan_bytes_per_device"]
        out["device_bytes_limit"] = memplan["device_bytes_limit"]
        if memplan.get("detail"):
            out["memplan_detail"] = memplan["detail"]

    agg = stage_out.get("agg")
    if agg is not None:
        # per-pytree, per-cohort aggregation throughput (tools/bench_watch.sh
        # surfaces agg_clients_per_sec from the artifact)
        out["agg_clients_per_sec"] = agg["agg_clients_per_sec"]
        out["agg_hbm_gbps"] = agg["agg_hbm_gbps"]
        out["agg_bucket_size"] = agg["agg_bucket_size"]
        out["agg_accum_traces"] = agg["agg_accum_traces"]
        if agg.get("agg_span_summary"):
            out["agg_span_summary"] = agg["agg_span_summary"]
        # resilience rider: async round-checkpoint enqueue cost (<5ms guard
        # inside the stage) + proof that watermark resume is bit-identical
        if agg.get("ckpt_enqueue_ms") is not None:
            out["ckpt_enqueue_ms"] = agg["ckpt_enqueue_ms"]
            out["resume_verified"] = agg["resume_verified"]

    agg_sharded = stage_out.get("agg_sharded")
    if agg_sharded is not None and "skipped" not in agg_sharded:
        # mesh-parallel server round headline trio (tools/bench_watch.sh
        # surfaces these): per-device HBM ratio vs the unsharded engine on
        # the same cohort (<=60% integrity-guarded in-stage), throughput,
        # and how much of the per-shard transfer hid under compute
        out["agg_sharded_hbm_ratio"] = agg_sharded["agg_sharded_hbm_ratio"]
        out["agg_sharded_clients_per_sec"] = agg_sharded["agg_sharded_clients_per_sec"]
        out["agg_sharded_overlap_efficiency"] = agg_sharded[
            "agg_sharded_overlap_efficiency"]
        out["agg_sharded_traces"] = agg_sharded["agg_sharded_traces"]
        if agg_sharded.get("agg_sharded_platform"):
            out["agg_sharded_platform"] = agg_sharded["agg_sharded_platform"]
    elif agg_sharded is not None:
        out["agg_sharded_skipped"] = agg_sharded["skipped"]

    async_rounds = stage_out.get("async_rounds")
    if async_rounds is not None and "skipped" not in async_rounds:
        # buffered-async headline (tools/bench_watch.sh surfaces these):
        # rounds/hr per cohort with the 1.1x flatness guard + both parity
        # guards asserted in-stage
        for key in ("async_rounds_per_hr", "async_flatness_ratio",
                    "async_staleness_p50", "async_staleness_p99",
                    "async_buffer_high_water", "async_publish_k",
                    "async_parity_bit_exact", "async_parity_multibucket_rel_err",
                    "async_server_merge_us", "async_hierarchy"):
            if async_rounds.get(key) is not None:
                out[key] = async_rounds[key]
    elif async_rounds is not None:
        out["async_rounds_skipped"] = async_rounds["skipped"]

    wan = stage_out.get("wan_profile")
    if wan is not None and "skipped" not in wan:
        # per-link WAN headline (tools/bench_watch.sh surfaces these):
        # worst estimator error vs the injected profile + probe overhead,
        # both integrity-guarded in-stage; the per-pair table rides along
        for key in ("wan_profile", "link_bw_error_pct", "probe_overhead_pct",
                    "wan_probe_ticks", "wan_probes_sent",
                    "wan_probes_answered", "wan_probe_payload_bytes",
                    "wan_window_s"):
            if wan.get(key) is not None:
                out[key] = wan[key]
    elif wan is not None:
        out["wan_profile_skipped"] = wan["skipped"]

    pipe = stage_out.get("pipeline_overlap")
    if pipe is not None and "skipped" not in pipe:
        # pipelined round-execution headline (tools/bench_watch.sh surfaces
        # these): measured overlap fraction + pipelined-vs-serial speedup,
        # both integrity-guarded in-stage; the planner's pick rides along
        for key in ("pipeline_overlap_frac", "pipeline_overlap_frac_min",
                    "pipeline_speedup", "pipeline_serial_wall_s",
                    "pipeline_wall_s", "pipeline_micro_batches",
                    "pipeline_chunk_nbytes", "pipeline_plan_reason",
                    "pipeline_clients", "pipeline_bottleneck"):
            if pipe.get(key) is not None:
                out[key] = pipe[key]
    elif pipe is not None:
        out["pipeline_overlap_skipped"] = pipe["skipped"]

    slo_out = stage_out.get("slo_overhead")
    if slo_out is not None and "skipped" not in slo_out:
        # SLO evaluator headline (tools/bench_watch.sh surfaces these):
        # evaluator cost share of the round loop + alerts fired during the
        # measurement, both integrity-guarded in-stage
        for key in ("slo_overhead_pct", "slo_ticks", "slo_ingest_ms",
                    "slo_tick_ms", "slo_samples", "alerts_fired",
                    "slo_rounds", "slo_window_s"):
            if slo_out.get(key) is not None:
                out[key] = slo_out[key]
    elif slo_out is not None:
        out["slo_overhead_skipped"] = slo_out["skipped"]

    mw_out = stage_out.get("modelwatch_overhead")
    if mw_out is not None and "skipped" not in mw_out:
        # modelwatch headline (tools/bench_watch.sh surfaces these): the
        # fold-boundary stats' cost share of a round-shaped loop + the
        # detection liveness count, both integrity-guarded in-stage
        for key in ("modelwatch_overhead_pct", "modelwatch_plain_round_ms",
                    "modelwatch_watched_round_ms", "modelwatch_fold_ms",
                    "modelwatch_rounds", "modelwatch_clients",
                    "modelwatch_work_reps", "modelwatch_detection_caught"):
            if mw_out.get(key) is not None:
                out[key] = mw_out[key]
    elif mw_out is not None:
        out["modelwatch_overhead_skipped"] = mw_out["skipped"]

    sa_out = stage_out.get("secagg_overhead")
    if sa_out is not None and "skipped" not in sa_out:
        # secagg+DP headline (tools/bench_watch.sh surfaces these): the
        # masking+noised-fold cost share of a round-shaped loop + the
        # epsilon the measurement itself spent, both integrity-guarded
        # in-stage (parity, mask-off bit-identity, accountant liveness)
        for key in ("secagg_overhead_pct", "secagg_plain_round_ms",
                    "secagg_masked_round_ms", "secagg_fold_ms",
                    "secagg_rounds", "secagg_clients", "secagg_model_dim",
                    "dp_epsilon_spent", "dp_noise_multiplier"):
            if sa_out.get(key) is not None:
                out[key] = sa_out[key]
    elif sa_out is not None:
        out["secagg_overhead_skipped"] = sa_out["skipped"]

    devperf_out = stage_out.get("devperf_overhead")
    if devperf_out is not None and "skipped" not in devperf_out:
        # devperf headline (tools/bench_watch.sh surfaces these): the live
        # registry's MFU for the llama step (must track the analytic MFU —
        # integrity-guarded in-stage) + the registry's cost share of wall
        for key in ("llm_mfu", "llm_mfu_analytic", "llm_mfu_rel_err",
                    "devperf_overhead_pct", "devperf_flops_source",
                    "devperf_xla_vs_analytic_flops_ratio",
                    "devperf_roofline_verdict", "devperf_steps",
                    "devperf_window_s", "devperf_hbm_samples"):
            if devperf_out.get(key) is not None:
                out[key] = devperf_out[key]
    elif devperf_out is not None:
        out["devperf_overhead_skipped"] = devperf_out["skipped"]

    fleet_out = stage_out.get("fleet_scale")
    if fleet_out is not None and "skipped" not in fleet_out:
        # fleet-sketch headline (tools/bench_watch.sh surfaces these):
        # sketch quantile accuracy vs exact + telemetry memory per client at
        # the million-client ingest, both integrity-guarded in-stage
        for key in ("fleet_scale_clients", "fleet_scale_nodes",
                    "fleet_scale_quantile_err_pct",
                    "fleet_telemetry_bytes_per_client",
                    "fleet_scale_total_sketch_bytes",
                    "fleet_scale_mem_ratio_vs_ref",
                    "fleet_scale_ingest_overhead_pct",
                    "fleet_scale_edge_eq_flat",
                    "fleet_scale_offenders_recovered",
                    "fleet_scale_hll_err_pct"):
            if fleet_out.get(key) is not None:
                out[key] = fleet_out[key]
    elif fleet_out is not None:
        out["fleet_scale_skipped"] = fleet_out["skipped"]

    placement = stage_out.get("placement_search")
    if placement is not None and "skipped" not in placement:
        # auto-placement headline (tools/bench_watch.sh surfaces these):
        # searched-vs-default speedup per workload, plus the winning plan's
        # fingerprint/knobs; the full PlacementPlan JSON is its own
        # committed artifact (placement_plan_files)
        for key in ("placement_plan", "placement_speedup",
                    "placement_plan_files", "placement_candidates"):
            if placement.get(key) is not None:
                out[key] = placement[key]
    elif placement is not None:
        out["placement_search_skipped"] = placement["skipped"]

    attn = stage_out.get("attn_micro")
    if attn is not None:
        out["attn_fwd_bwd_ms"] = attn["fwd_bwd_ms"]
        if attn.get("rejected_configs"):
            out["attn_rejected_configs"] = attn["rejected_configs"]
        if attn.get("best_flash") is not None:
            out["attn_best_flash"] = attn["best_flash"]
            out["attn_best_vs_einsum"] = attn["best_vs_einsum"]

    if stage_out:
        _write_measured_artifact(dict(out, _stages=merged), stamp)
    print(json.dumps(out))
    # rc contract: 0 whenever the HEADLINE number exists — secondary-stage
    # failures are recorded in stages_failed, not fatal (VERDICT r3 item 1)
    sys.exit(0 if llm is not None else 1)


def main_short(budget_s: int = 240) -> None:
    """Short-window bench (VERDICT r4 weak #2): probe -> ONE fast pallas
    headline stage -> artifact, sized to survive a ~3-minute tunnel window
    with the persistent compile cache warm. vs_baseline comes from the
    banked CPU denominators (BENCH_CPU_BASELINES.json), never re-measured
    here. rc 0 iff a headline number landed."""
    import signal

    signal.signal(signal.SIGTERM, _handle_term)
    signal.signal(signal.SIGINT, _handle_term)
    watcher = os.environ.get("FEDML_BENCH_WATCHER") == "1"
    lock = _acquire_bench_lock(watcher)
    if watcher and lock is None:
        print(json.dumps({"skipped": "bench_lock_held",
                          "last_measured": _last_measured()}))
        sys.exit(1)
    try:
        _probe_backend(timeout_s=60)
    except BenchProbeTimeout as e:
        print(json.dumps({"skipped": "tunnel_stalled", "short_window": True,
                          "detail": str(e)}))
        sys.exit(1)

    stamp = datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    env = _flash_blocks_env(_flash_mode_env() or dict(os.environ))
    env["FEDML_BENCH_FAST"] = "1"
    result, err = _spawn_stage("llm_pallas", budget_s, env=env)
    if err is not None:
        print(json.dumps({"skipped": "short_window_stage_failed", "detail": err,
                          "last_measured": _last_measured()}))
        sys.exit(1)
    tiny = os.environ.get("FEDML_BENCH_TINY") == "1"
    banked = _load_cpu_baselines() or {}
    # the banked denominator is FLAGSHIP-geometry torch-CPU: a tiny dry-run
    # ratio against it would be meaningless
    cpu_llm = None if tiny else banked.get("cpu_llm_tokens_per_sec")
    out = {
        "metric": "llm_train_tokens_per_sec",
        "value": round(result["tokens_per_sec"], 1),
        "unit": f"tokens/s (llama-{result['n_params'] / 1e6:.0f}M full train step, "
                f"bf16, seq{result['shape']['seq']} bs{result['shape']['bs']}, "
                f"1x {result['device']})",
        "vs_baseline": round(result["tokens_per_sec"] / cpu_llm, 2) if cpu_llm else None,
        "mfu": round(result["mfu"], 4),
        "attention_impl": result["attention_impl"],
        "remat": result["remat"],
        "short_window": True,
    }
    if tiny:
        out["tiny_dryrun"] = True
    if banked and cpu_llm is not None:
        out["cpu_baseline_source"] = f"banked {banked.get('measured_at_utc')}"
    if _PROCEEDED_UNLOCKED:
        out["bench_lock"] = "proceeded_unlocked"
    _write_measured_artifact(dict(out, _stages={"_llm_pallas": result}), stamp)
    print(json.dumps(out))
    sys.exit(0)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--stage", help="run one measurement stage and print its JSON")
    parser.add_argument("--trace", metavar="OUT.json",
                        help="with --stage: wrap the stage in a telemetry span and "
                             "write a Chrome-trace/Perfetto JSON of it to this path; "
                             "an existing trace file is merged into, so multi-stage "
                             "runs sharing one path keep every stage's spans")
    parser.add_argument("--short-window", action="store_true",
                        help="probe + one fast pallas headline stage, ~3-min budget")
    parser.add_argument("--cpu-baselines", action="store_true",
                        help="(re)measure and bank the torch-CPU denominators; no chip needed")
    ns = parser.parse_args()
    if ns.trace and not ns.stage:
        parser.error("--trace requires --stage")
    if ns.stage:
        _run_stage(ns.stage, trace=ns.trace)
    elif ns.cpu_baselines:
        banked = _ensure_cpu_baselines(force=True)
        print(json.dumps(banked or {"error": "cpu baseline stages failed"}))
        sys.exit(0 if banked else 1)
    elif ns.short_window:
        main_short()
    else:
        main()
