"""Benchmark: FedAvg client local-training throughput (the north-star
"client local steps/sec", BASELINE.md) on the real attached accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline: ratio against a torch-CPU implementation of the same local-SGD
workload (the reference is torch; no CUDA exists here, so torch-CPU is the
honest reproducible baseline on this machine — see BASELINE.md: reference
publishes no numbers of its own).
"""

from __future__ import annotations

import json
import time

import numpy as np


def _bench_fedml_tpu(steps: int, batch_size: int, model_name: str = "cnn") -> float:
    import jax
    import jax.numpy as jnp

    from fedml_tpu.arguments import default_config
    from fedml_tpu.ml.trainer.local_sgd import epoch_index_array, make_local_train_fn
    from fedml_tpu.models.model_hub import create

    args = default_config("simulation", model=model_name, dataset="mnist", batch_size=batch_size, epochs=1)
    model = create(args, 10)
    local_train = make_local_train_fn(model, args)

    n = steps * batch_size
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, 28, 28, 1)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, n).astype(np.int32))
    idx, mask = epoch_index_array(n, batch_size, 1, 0)
    idx, mask = jnp.asarray(idx), jnp.asarray(mask)
    key = jax.random.PRNGKey(0)

    # warmup/compile
    jax.block_until_ready(local_train(model.params, x, y, idx, mask, key, None).params)
    t0 = time.perf_counter()
    reps = 5
    params = model.params
    for i in range(reps):
        params = local_train(params, x, y, idx, mask, key, None).params
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    return steps * reps / dt


def _bench_torch_cpu(steps: int, batch_size: int) -> float:
    """Reference-style torch CPU loop: same CNN shape, same workload."""
    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    torch.set_num_threads(max(1, torch.get_num_threads()))

    class CNN(nn.Module):
        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2d(1, 32, 3)
            self.c2 = nn.Conv2d(32, 64, 3)
            self.f1 = nn.Linear(64 * 5 * 5, 128)
            self.f2 = nn.Linear(128, 10)

        def forward(self, x):
            x = F.max_pool2d(F.relu(self.c1(x)), 2)
            x = F.max_pool2d(F.relu(self.c2(x)), 2)
            x = x.flatten(1)
            return self.f2(F.relu(self.f1(x)))

    model = CNN()
    opt = torch.optim.SGD(model.parameters(), lr=0.03)
    rng = np.random.default_rng(0)
    x = torch.tensor(rng.normal(size=(steps, batch_size, 1, 28, 28)).astype(np.float32))
    y = torch.tensor(rng.integers(0, 10, (steps, batch_size)))
    # warmup
    for i in range(3):
        opt.zero_grad()
        F.cross_entropy(model(x[i]), y[i]).backward()
        opt.step()
    t0 = time.perf_counter()
    n_done = 0
    while time.perf_counter() - t0 < 5.0:
        i = n_done % steps
        opt.zero_grad()
        F.cross_entropy(model(x[i]), y[i]).backward()
        opt.step()
        n_done += 1
    return n_done / (time.perf_counter() - t0)


def main() -> None:
    steps, batch = 64, 64
    tpu_rate = _bench_fedml_tpu(steps, batch)
    try:
        torch_rate = _bench_torch_cpu(steps, batch)
    except Exception:
        torch_rate = None
    print(
        json.dumps(
            {
                "metric": "fedavg_client_local_steps_per_sec",
                "value": round(tpu_rate, 2),
                "unit": "steps/s (CNN-MNIST bs=64)",
                "vs_baseline": round(tpu_rate / torch_rate, 2) if torch_rate else None,
            }
        )
    )


if __name__ == "__main__":
    main()
