#!/usr/bin/env python3
"""Timing-idiom lint — thin shim over ``tools.fedlint`` (rule: wall-clock).

The walker that lived here (PR 2) is now ``tools/fedlint/rules/timing.py``;
this shim preserves the historical contract — ``find_violations(root)``
tuples, stdout format, exit codes (0 clean / 1 violations) — for
tier-1 callers (tests/test_telemetry.py) and the sibling shims that
re-run it. New callers should use ``python -m tools.fedlint`` directly.

Rule: ``time.time()`` durations are forbidden — NTP steps/slew corrupt
them; use ``fedml_tpu.core.telemetry`` (perf_counter-based). Genuine
timestamps/deadlines are suppressed with the unified pragma
``# fedlint: disable=wall-clock <reason>`` (the legacy
``# wall-clock ok: <reason>`` marker is still honored).
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.fedlint import api  # noqa: E402

MARKER = "wall-clock ok"


def find_violations(root: str) -> list:
    """Legacy shape: (path, lineno, stripped source line)."""
    result = api.run_rules(root, ["wall-clock"])
    return [(f.path, f.line, f.line_text.strip())
            for f in result.findings if f.rule == "wall-clock"]


def main(argv: list = ()) -> int:
    root = argv[0] if argv else os.path.join(_REPO, "fedml_tpu")
    violations = find_violations(root)
    for path, lineno, line in violations:
        print(f"{os.path.relpath(path, _REPO)}:{lineno}: unmarked time.time(): {line}")
    if violations:
        print(
            f"\n{len(violations)} unmarked time.time() call(s). Durations must use "
            "fedml_tpu.core.telemetry (span/timed/histogram, perf_counter-based); "
            "genuine timestamps/deadlines need a "
            "'# fedlint: disable=wall-clock <reason>' suppression."
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
