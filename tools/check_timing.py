#!/usr/bin/env python3
"""Timing-idiom lint: no new ``time.time()`` duration measurements.

``time.time()`` follows the wall clock — NTP steps and slew corrupt any
duration computed from it (a negative "aggregate time" poisons runtime fits
and autoscaling). Durations belong to the telemetry layer
(``fedml_tpu/core/telemetry``: span/timed/histogram, perf_counter-based).

The rule enforced over every ``fedml_tpu/**/*.py`` file: a line containing
``time.time()`` must carry a ``# wall-clock ok: <reason>`` marker on the same
line. The marker is the allowlist — legitimate uses are *timestamps* (record
fields, DB rows) and *wall deadlines* (timeouts coordinated with other
processes), and the reason says which. Anything unmarked fails tier-1
(tests/test_telemetry.py invokes ``main()``).

Exit status: 0 clean, 1 with violations listed on stdout.
"""

from __future__ import annotations

import os
import sys

MARKER = "wall-clock ok"
PATTERN = "time.time()"  # substring: also catches `_time.time()` aliases


def find_violations(root: str) -> list:
    violations = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if PATTERN in line and MARKER not in line:
                        violations.append((path, lineno, line.strip()))
    return violations


def main(argv: list = ()) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = argv[0] if argv else os.path.join(repo, "fedml_tpu")
    violations = find_violations(root)
    for path, lineno, line in violations:
        print(f"{os.path.relpath(path, repo)}:{lineno}: unmarked time.time(): {line}")
    if violations:
        print(
            f"\n{len(violations)} unmarked time.time() call(s). Durations must use "
            "fedml_tpu.core.telemetry (span/timed/histogram, perf_counter-based); "
            f"genuine timestamps/deadlines need a '# {MARKER}: <reason>' marker."
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
