#!/usr/bin/env python3
"""Telemetry-hygiene lint — thin shim over ``tools.fedlint`` (rules:
reserved-key, wall-clock, recorder-kind, excepthook).

The four line-scan walkers that lived here (PRs 3–4) are now
``tools/fedlint/rules/telemetry.py`` (AST-based); this shim preserves the
historical contract — per-rule ``find_*_violations(root)`` tuples, stdout
format, exit codes — for tier-1 callers (tests/test_trace_propagation.py,
tests/test_flight_recorder.py). New callers use ``python -m tools.fedlint``.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.fedlint import api  # noqa: E402


def _tuples(root: str, rule: str) -> list:
    result = api.run_rules(root, [rule])
    return [(f.path, f.line, f.line_text.strip())
            for f in result.findings if f.rule == rule]


def find_reserved_key_violations(root: str) -> list:
    return _tuples(root, "reserved-key")


def find_recorder_kind_violations(root: str) -> list:
    return _tuples(root, "recorder-kind")


def find_excepthook_violations(root: str) -> list:
    return _tuples(root, "excepthook")


def main(argv: list = ()) -> int:
    root = argv[0] if argv else os.path.join(_REPO, "fedml_tpu")
    rc = 0

    reserved = find_reserved_key_violations(root)
    for path, lineno, line in reserved:
        print(f"{os.path.relpath(path, _REPO)}:{lineno}: raw reserved telemetry key: {line}")
    if reserved:
        print(
            f"\n{len(reserved)} raw use(s) of the reserved telemetry header key. "
            "Use Message.MSG_ARG_KEY_TELEMETRY (or trace_context."
            "RESERVED_TELEMETRY_KEY) — payload keys must never collide with it."
        )
        rc = 1

    timing = _tuples(root, "wall-clock")
    for path, lineno, line in timing:
        print(f"{os.path.relpath(path, _REPO)}:{lineno}: unmarked time.time(): {line}")
    if timing:
        print(
            f"\n{len(timing)} unmarked time.time() call(s) — see tools/check_timing.py."
        )
        rc = 1

    kinds = find_recorder_kind_violations(root)
    for path, lineno, line in kinds:
        print(f"{os.path.relpath(path, _REPO)}:{lineno}: raw recorder event kind: {line}")
    if kinds:
        print(
            f"\n{len(kinds)} raw recorder event-kind literal(s). Use the "
            "flight_recorder.EVENT_* constants via record_event/mark/"
            "record_comm — ad-hoc kinds are invisible to tools/fr_dump.py."
        )
        rc = 1

    hooks = find_excepthook_violations(root)
    for path, lineno, line in hooks:
        print(f"{os.path.relpath(path, _REPO)}:{lineno}: excepthook outside flight_recorder: {line}")
    if hooks:
        print(
            f"\n{len(hooks)} excepthook reference(s) outside "
            "core/telemetry/flight_recorder.py. Crash handling has ONE owner: "
            "use flight_recorder.install()/installed() instead."
        )
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
