#!/usr/bin/env python3
"""Telemetry-hygiene lint (tier-1 enforced; tests/test_telemetry.py runs it).

Four rules over ``fedml_tpu/**/*.py``:

1. **Reserved-header containment.** The comm layer reserves one ``Message``
   parameter key for the trace-context + delta-snapshot header. The string
   literal must appear ONLY in ``core/telemetry/trace_context.py`` (its
   canonical home); everywhere else must reference
   ``trace_context.RESERVED_TELEMETRY_KEY`` / ``Message.MSG_ARG_KEY_TELEMETRY``.
   A payload constructed from the raw literal would silently collide with the
   header and be clobbered by ``inject()`` on send.

2. **Timing-idiom regressions.** Re-runs ``check_timing.find_violations`` so
   one tool invocation covers both lints (new ad-hoc ``time.time()`` calls
   still need their ``# wall-clock ok:`` marker).

3. **Recorder event-kind containment.** The flight recorder's event-kind
   literals ("span_open" etc.) belong ONLY to
   ``core/telemetry/flight_recorder.py``; ad-hoc producers spelling them
   elsewhere would invent look-alike events ``tools/fr_dump.py`` cannot
   interpret. Everything else records via ``flight_recorder.record_event``
   with the EVENT_* constants (or ``mark``/``record_comm``).

4. **Excepthook containment.** ``sys.excepthook`` / ``threading.excepthook``
   may be touched ONLY by ``core/telemetry/flight_recorder.py`` — a second
   installer would silently drop crash dumps (or the other hook), depending
   on import order.

Exit status: 0 clean, 1 with violations listed on stdout.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import check_timing  # noqa: E402

# The reserved key, spelled fragment-wise so THIS file does not trip its own
# lint when scanned.
RESERVED = "__" + "telemetry" + "__"
# The one module allowed to spell the literal (relative to the scan root).
ALLOWED_FILES = (os.path.join("core", "telemetry", "trace_context.py"),)

# The one module allowed to spell recorder event kinds or touch excepthooks.
FLIGHT_RECORDER = os.path.join("core", "telemetry", "flight_recorder.py")
# Distinctive kind literals only — generic words ("exception", "mark") would
# false-positive across the tree.
RECORDER_KINDS = ("span_open", "span_close", "comm_send", "comm_recv")
EXCEPTHOOK_NEEDLES = ("sys.excepthook", "threading.excepthook")


def _scan(root: str, match, allowed: tuple) -> list:
    """Generic line scan: ``match(line) -> bool`` over .py files outside
    ``allowed`` (paths relative to the scan root)."""
    violations = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            if rel in allowed:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if match(line):
                        violations.append((path, lineno, line.strip()))
    return violations


def find_reserved_key_violations(root: str) -> list:
    needles = ('"' + RESERVED + '"', "'" + RESERVED + "'")
    return _scan(root, lambda line: any(n in line for n in needles), ALLOWED_FILES)


def find_recorder_kind_violations(root: str) -> list:
    """Quoted recorder event-kind literals outside flight_recorder.py."""
    needles = tuple('"' + k + '"' for k in RECORDER_KINDS) + tuple(
        "'" + k + "'" for k in RECORDER_KINDS
    )
    return _scan(root, lambda line: any(n in line for n in needles),
                 (FLIGHT_RECORDER,))


def find_excepthook_violations(root: str) -> list:
    """sys/threading excepthook references outside flight_recorder.py."""
    return _scan(root, lambda line: any(n in line for n in EXCEPTHOOK_NEEDLES),
                 (FLIGHT_RECORDER,))


def main(argv: list = ()) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = argv[0] if argv else os.path.join(repo, "fedml_tpu")
    rc = 0

    reserved = find_reserved_key_violations(root)
    for path, lineno, line in reserved:
        print(f"{os.path.relpath(path, repo)}:{lineno}: raw reserved telemetry key: {line}")
    if reserved:
        print(
            f"\n{len(reserved)} raw use(s) of the reserved telemetry header key. "
            "Use Message.MSG_ARG_KEY_TELEMETRY (or trace_context."
            "RESERVED_TELEMETRY_KEY) — payload keys must never collide with it."
        )
        rc = 1

    timing = check_timing.find_violations(root)
    for path, lineno, line in timing:
        print(f"{os.path.relpath(path, repo)}:{lineno}: unmarked time.time(): {line}")
    if timing:
        print(
            f"\n{len(timing)} unmarked time.time() call(s) — see tools/check_timing.py."
        )
        rc = 1

    kinds = find_recorder_kind_violations(root)
    for path, lineno, line in kinds:
        print(f"{os.path.relpath(path, repo)}:{lineno}: raw recorder event kind: {line}")
    if kinds:
        print(
            f"\n{len(kinds)} raw recorder event-kind literal(s). Use the "
            "flight_recorder.EVENT_* constants via record_event/mark/"
            "record_comm — ad-hoc kinds are invisible to tools/fr_dump.py."
        )
        rc = 1

    hooks = find_excepthook_violations(root)
    for path, lineno, line in hooks:
        print(f"{os.path.relpath(path, repo)}:{lineno}: excepthook outside flight_recorder: {line}")
    if hooks:
        print(
            f"\n{len(hooks)} excepthook reference(s) outside "
            "core/telemetry/flight_recorder.py. Crash handling has ONE owner: "
            "use flight_recorder.install()/installed() instead."
        )
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
