#!/usr/bin/env python3
"""Telemetry-hygiene lint (tier-1 enforced; tests/test_telemetry.py runs it).

Two rules over ``fedml_tpu/**/*.py``:

1. **Reserved-header containment.** The comm layer reserves one ``Message``
   parameter key for the trace-context + delta-snapshot header. The string
   literal must appear ONLY in ``core/telemetry/trace_context.py`` (its
   canonical home); everywhere else must reference
   ``trace_context.RESERVED_TELEMETRY_KEY`` / ``Message.MSG_ARG_KEY_TELEMETRY``.
   A payload constructed from the raw literal would silently collide with the
   header and be clobbered by ``inject()`` on send.

2. **Timing-idiom regressions.** Re-runs ``check_timing.find_violations`` so
   one tool invocation covers both lints (new ad-hoc ``time.time()`` calls
   still need their ``# wall-clock ok:`` marker).

Exit status: 0 clean, 1 with violations listed on stdout.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import check_timing  # noqa: E402

# The reserved key, spelled fragment-wise so THIS file does not trip its own
# lint when scanned.
RESERVED = "__" + "telemetry" + "__"
# The one module allowed to spell the literal (relative to the scan root).
ALLOWED_FILES = (os.path.join("core", "telemetry", "trace_context.py"),)


def find_reserved_key_violations(root: str) -> list:
    violations = []
    needles = ('"' + RESERVED + '"', "'" + RESERVED + "'")
    for dirpath, _dirnames, filenames in os.walk(root):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            if rel in ALLOWED_FILES:
                continue
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if any(n in line for n in needles):
                        violations.append((path, lineno, line.strip()))
    return violations


def main(argv: list = ()) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = argv[0] if argv else os.path.join(repo, "fedml_tpu")
    rc = 0

    reserved = find_reserved_key_violations(root)
    for path, lineno, line in reserved:
        print(f"{os.path.relpath(path, repo)}:{lineno}: raw reserved telemetry key: {line}")
    if reserved:
        print(
            f"\n{len(reserved)} raw use(s) of the reserved telemetry header key. "
            "Use Message.MSG_ARG_KEY_TELEMETRY (or trace_context."
            "RESERVED_TELEMETRY_KEY) — payload keys must never collide with it."
        )
        rc = 1

    timing = check_timing.find_violations(root)
    for path, lineno, line in timing:
        print(f"{os.path.relpath(path, repo)}:{lineno}: unmarked time.time(): {line}")
    if timing:
        print(
            f"\n{len(timing)} unmarked time.time() call(s) — see tools/check_timing.py."
        )
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
