#!/usr/bin/env python3
"""Serving-hygiene lint (tier-1 enforced; tests/test_continuous_batching.py
runs it).

Two rules over ``fedml_tpu/serving/**/*.py``:

1. **Hot loops carry telemetry spans.** The serving hot paths — the
   continuous-batching engine's admit/step loop and the gateway's forward
   path — must time themselves through ``tel.timed(``/``tel.span(`` (which
   are perf_counter-based): an uninstrumented hot loop is how the r05
   endpoint collapse (14.5 tok/s against a 370k tok/s chip) stayed
   invisible until a full bench window. The registry below names the
   functions that MUST contain a span call; deleting the instrumentation
   without updating the registry fails tier-1.

2. **No wall-clock durations.** Latency math in serving must ride
   ``time.perf_counter()``; ad-hoc ``time.time()`` needs the repo-wide
   ``# wall-clock ok:`` marker (re-runs ``check_timing.find_violations``
   scoped to serving/, so one tool covers both lints for this subtree).

Exit status: 0 clean, 1 with violations listed on stdout.
"""

from __future__ import annotations

import ast
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import check_timing  # noqa: E402

# (relative path under the scan root, qualified function name) -> every
# listed function must contain a tel.timed(/tel.span( call somewhere in its
# body. "Class.method" pins one method; a bare name matches module level.
HOT_LOOPS: tuple[tuple[str, str], ...] = (
    ("continuous_batching.py", "ContinuousBatchingEngine._admit_all"),
    ("continuous_batching.py", "ContinuousBatchingEngine._step_chunk"),
    ("replica_controller.py", "InferenceGateway.predict"),
)

_SPAN_ATTRS = ("timed", "span")


def _calls_span(node: ast.AST) -> bool:
    """True if any call inside ``node`` is tel.timed(...) / tel.span(...)
    (any receiver named like the telemetry module counts — serving imports
    it as ``tel``)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            if sub.func.attr in _SPAN_ATTRS:
                return True
    return False


def find_unspanned_hot_loops(root: str) -> list:
    """HOT_LOOPS entries whose function exists but contains no span call
    (a registry entry whose file/function is GONE is also a violation —
    silently skipping it would let a rename drop the guard)."""
    violations = []
    for rel, fn_name in HOT_LOOPS:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            violations.append((path, 0, f"registry names missing file {rel}"))
            continue
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        cls_name, _, meth = fn_name.rpartition(".")
        if cls_name:
            scopes = [n for n in ast.walk(tree)
                      if isinstance(n, ast.ClassDef) and n.name == cls_name]
        else:
            scopes = [tree]
        found = False
        for scope in scopes:
            for node in scope.body if cls_name else ast.walk(scope):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name == meth:
                    found = True
                    if not _calls_span(node):
                        violations.append(
                            (path, node.lineno,
                             f"hot loop {fn_name}() has no tel.timed()/tel.span()"))
        if not found:
            violations.append(
                (path, 0, f"registry names missing function {fn_name}()"))
    return violations


def main(argv: list = ()) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = argv[0] if argv else os.path.join(repo, "fedml_tpu", "serving")
    rc = 0

    unspanned = find_unspanned_hot_loops(root)
    for path, lineno, msg in unspanned:
        print(f"{os.path.relpath(path, repo)}:{lineno}: {msg}")
    if unspanned:
        print(
            f"\n{len(unspanned)} uninstrumented serving hot loop(s). Wrap the "
            "device-touching section in tel.timed('serving....') so TTFT/TPOT "
            "regressions show up in /metrics and traces, not in bench windows."
        )
        rc = 1

    timing = check_timing.find_violations(root)
    for path, lineno, line in timing:
        print(f"{os.path.relpath(path, repo)}:{lineno}: unmarked time.time(): {line}")
    if timing:
        print(
            f"\n{len(timing)} unmarked time.time() call(s) in serving — "
            "durations must use time.perf_counter() (see tools/check_timing.py)."
        )
        rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
