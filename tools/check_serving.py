#!/usr/bin/env python3
"""Serving-hygiene lint — thin shim over ``tools.fedlint`` (rules:
hot-span, wall-clock).

The AST walker that lived here (PR 6) is now
``tools/fedlint/rules/serving.py``; this shim preserves the historical
contract — ``find_unspanned_hot_loops(root)`` tuples, stdout format, exit
codes — for tier-1 callers (tests/test_continuous_batching.py). The hot-
loop registry itself now lives in the rule module (HOT_LOOPS). New callers
use ``python -m tools.fedlint``.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.fedlint import api  # noqa: E402
from tools.fedlint.rules.serving import HOT_LOOPS  # noqa: E402,F401 (re-export)


def find_unspanned_hot_loops(root: str) -> list:
    """Legacy shape: (path, lineno, message)."""
    result = api.run_rules(root, ["hot-span"])
    return [(f.path, f.line, f.message)
            for f in result.findings if f.rule == "hot-span"]


def main(argv: list = ()) -> int:
    root = argv[0] if argv else os.path.join(_REPO, "fedml_tpu", "serving")
    rc = 0

    unspanned = find_unspanned_hot_loops(root)
    for path, lineno, msg in unspanned:
        print(f"{os.path.relpath(path, _REPO)}:{lineno}: {msg}")
    if unspanned:
        print(
            f"\n{len(unspanned)} uninstrumented serving hot loop(s). Wrap the "
            "device-touching section in tel.timed('serving....') so TTFT/TPOT "
            "regressions show up in /metrics and traces, not in bench windows."
        )
        rc = 1

    result = api.run_rules(root, ["wall-clock"])
    timing = [(f.path, f.line, f.line_text.strip())
              for f in result.findings if f.rule == "wall-clock"]
    for path, lineno, line in timing:
        print(f"{os.path.relpath(path, _REPO)}:{lineno}: unmarked time.time(): {line}")
    if timing:
        print(
            f"\n{len(timing)} unmarked time.time() call(s) in serving — "
            "durations must use time.perf_counter() (see tools/check_timing.py)."
        )
        rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
