#!/usr/bin/env bash
# Opportunistic bench watcher (VERDICT r2 next #1a).
#
# The remote TPU tunnel stalls for hours at a time, so a single capture at
# round end is likely to be red. This loop probes the tunnel cheaply; whenever
# it is up it runs bench.py (which writes a timestamped BENCH_MEASURED_*.json
# artifact on success) and commits the artifact immediately, so a verified
# number exists in git no matter what the tunnel is doing at capture time.
#
# Usage: nohup tools/bench_watch.sh >/tmp/bench_watch.log 2>&1 &
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"

PROBE_TIMEOUT=${PROBE_TIMEOUT:-90}
BENCH_TIMEOUT=${BENCH_TIMEOUT:-2400}
SLEEP_DOWN=${SLEEP_DOWN:-600}     # tunnel down: re-probe every 10 min
SLEEP_UP=${SLEEP_UP:-3600}        # after a good measurement: hourly is plenty

log() { echo "[$(date -u +%FT%TZ)] $*"; }

while true; do
  if timeout "$PROBE_TIMEOUT" python -c "import jax; print(jax.devices()[0])" >/dev/null 2>&1; then
    log "tunnel up — running bench.py"
    if timeout "$BENCH_TIMEOUT" python bench.py >/tmp/bench_watch_last.json 2>/tmp/bench_watch_last.err; then
      log "bench ok: $(cat /tmp/bench_watch_last.json)"
      # commit ONLY the artifact paths so a concurrent interactive commit's
      # staged files are never swept into this commit
      if compgen -G "BENCH_MEASURED_*.json" >/dev/null; then
        git add BENCH_MEASURED_*.json
        if git diff --cached --quiet -- BENCH_MEASURED_*.json; then
          log "no new artifact to commit"
        elif git commit -q -m "Record measured bench artifact from live chip" -- BENCH_MEASURED_*.json 2>/tmp/bench_watch_commit.err; then
          log "artifact committed"
        else
          log "COMMIT FAILED: $(tail -c 400 /tmp/bench_watch_commit.err)"
        fi
      fi
      sleep "$SLEEP_UP"
    else
      rc=$?
      if grep -q '"skipped": *"tunnel_stalled"' /tmp/bench_watch_last.json 2>/dev/null; then
        log "tunnel stalled mid-run (structured skip, rc=$rc)"
      else
        log "bench CRASHED (rc=$rc): $(tail -c 400 /tmp/bench_watch_last.err)"
      fi
      sleep "$SLEEP_DOWN"
    fi
  else
    log "tunnel down"
    sleep "$SLEEP_DOWN"
  fi
done
