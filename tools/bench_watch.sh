#!/usr/bin/env bash
# Opportunistic bench watcher (VERDICT r2 next #1a; rearchitected round 4).
#
# The remote TPU tunnel stalls for hours at a time, so a single capture at
# round end is likely to be red. This loop probes the tunnel cheaply; whenever
# it is up it (1) runs the one-off pallas flash-attention smoke once
# (ADVICE r3: the (block_q,1) lane layout had never met real Mosaic), then
# (2) runs bench.py — now stage-isolated subprocesses that write an
# incremental BENCH_MEASURED_*.json after EVERY successful stage — and
# commits whatever artifacts exist even if a later stage died, so verified
# numbers land in git no matter what the tunnel does mid-run.
#
# Usage: nohup tools/bench_watch.sh >/tmp/bench_watch.log 2>&1 &
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"

# bench lock lives under the repo (0700), not world-writable /tmp (ADVICE r4);
# path must match bench.py's _BENCH_LOCK_PATH
mkdir -p -m 700 "$REPO/.bench_runtime"
LOCK="$REPO/.bench_runtime/bench.lock"

PROBE_TIMEOUT=${PROBE_TIMEOUT:-90}
SMOKE_TIMEOUT=${SMOKE_TIMEOUT:-1200}  # may run BOTH stats layouts (narrow+wide)
# must exceed the sum of bench.py's per-stage budgets (_STAGES: 15360s with
# attn_micro, the tuned re-run, the agg + agg_sharded microbenches, the
# placement search, the wan_profile link-observability stage and the
# slo/modelwatch/devperf/secagg overhead guards; banked CPU baselines
# usually shave 600s) plus the 180s probe, or the outer timeout kills a
# run whose stages are all within their own contracts
BENCH_TIMEOUT=${BENCH_TIMEOUT:-16200}
SLEEP_DOWN=${SLEEP_DOWN:-120}     # tunnel down: re-probe every 2 min (short
                                  # up-windows are the norm; 10 min missed them)
SLEEP_UP=${SLEEP_UP:-3600}        # after a good measurement: hourly is plenty
SMOKE_STAMP=/tmp/fedml_smoke_passed
# the stamp is valid only for the kernel code it smoked: a changed
# flash_attention.py must be re-smoked on the next window
KERNEL_HASH=$(sha256sum "$REPO/fedml_tpu/ops/flash_attention.py" | cut -d' ' -f1)
if [ -f "$SMOKE_STAMP" ] && [ "$(cat "$SMOKE_STAMP" 2>/dev/null)" != "$KERNEL_HASH" ]; then
  rm -f "$SMOKE_STAMP"
fi

log() { echo "[$(date -u +%FT%TZ)] $*"; }

surface_fedlint() {
  # one-line static-analysis health check (docs/static_analysis.md): runs the
  # unified linter once at watcher startup so a window that begins with
  # unsuppressed findings (retrace risk, host syncs in hot loops, donation
  # misuse, lock discipline) is called out in the log before any chip time is
  # spent measuring code the lint already flags. Pure CPU/AST — no chip, no
  # lock needed. The summary line also carries the incremental-cache hit rate
  # and wall time ("cache 97% (8 analyzed) · 0.41s"), so consecutive watcher
  # starts double as a health check on .fedlint_cache.json: a warm start that
  # logs a cold hit rate means the cache is being invalidated every run.
  local summary
  summary=$(timeout 120 python -m tools.fedlint 2>/dev/null | tail -1) || true
  if [ -n "$summary" ]; then
    log "$summary"
  else
    log "fedlint: could not run (python -m tools.fedlint failed)"
  fi
}

commit_artifacts() {
  # commit ONLY the artifact paths so a concurrent interactive commit's
  # staged files are never swept into this commit. Pathspecs are collected
  # from files that actually exist: git add/commit with ANY unmatched
  # pathspec is fatal and does nothing (verified), so the baselines-only
  # and measured-only cases must each build their own list
  local paths=()
  while IFS= read -r f; do paths+=("$f"); done < <(compgen -G "BENCH_MEASURED_*.json")
  # winning placement plans (bench.py --stage placement_search) ride along:
  # a committed plan is what `args.placement=PATH` replays without re-probing
  while IFS= read -r f; do paths+=("$f"); done < <(compgen -G "PLACEMENT_PLAN_*.json")
  [ -f BENCH_CPU_BASELINES.json ] && paths+=(BENCH_CPU_BASELINES.json)
  if [ "${#paths[@]}" -gt 0 ]; then
    git add -- "${paths[@]}"
    if git diff --cached --quiet -- "${paths[@]}"; then
      log "no new artifact to commit"
    elif git commit -q -m "Record measured bench artifact from live chip" -- "${paths[@]}" 2>/tmp/bench_watch_commit.err; then
      log "artifact committed: $(git rev-parse --short HEAD)"
      surface_agg_rates
      surface_agg_sharded
      surface_async_rounds
      surface_wan_profile
      surface_pipeline_overlap
      surface_devperf
      surface_modelwatch
      surface_secagg
      surface_fleet_scale
      surface_placement
      surface_resilience
      surface_serving
      surface_span_summary
      surface_alerts
      surface_trace_files
      surface_crash_dumps
      surface_bench_regress
    else
      log "COMMIT FAILED: $(tail -c 400 /tmp/bench_watch_commit.err)"
    fi
  fi
}

surface_agg_rates() {
  # one-line view of the aggregation-engine measurement in the newest
  # artifact, so the watcher log answers "how fast is agg on chip" without
  # opening BENCH_MEASURED_*.json
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local rates
  rates=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
agg = doc.get("agg_clients_per_sec") or {}
if agg:
    parts = [f"{label} {{{', '.join(f'K={k}: {v}/s' for k, v in r.items())}}}"
             for label, r in agg.items()]
    print(f"agg_clients_per_sec (bucket={doc.get('agg_bucket_size')}): " + "; ".join(parts))
PYEOF
) || return 0
  [ -n "$rates" ] && log "$rates"
}

surface_agg_sharded() {
  # one-line view of the mesh-parallel server round in the newest artifact:
  # per-device HBM ratio vs the unsharded engine (<=0.60 guarded in-stage),
  # throughput, ingestion-overlap efficiency, and the zero-recompile trace
  # count — so the watcher log answers "did sharding actually shrink the
  # server's per-chip footprint" without opening BENCH_MEASURED_*.json
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local sharded
  sharded=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("agg_sharded_hbm_ratio") is not None:
    extra = f" [{doc['agg_sharded_platform']}]" if doc.get("agg_sharded_platform") else ""
    print(f"agg_sharded: hbm_ratio {doc['agg_sharded_hbm_ratio']}, "
          f"{doc.get('agg_sharded_clients_per_sec')} clients/s, "
          f"overlap_eff {doc.get('agg_sharded_overlap_efficiency')}, "
          f"traces {doc.get('agg_sharded_traces')}{extra}")
elif doc.get("agg_sharded_skipped"):
    print(f"agg_sharded: skipped ({doc['agg_sharded_skipped']})")
PYEOF
) || return 0
  [ -n "$sharded" ] && log "$sharded"
}

surface_async_rounds() {
  # one-line view of the async buffered-federation stage: rounds/hr per
  # cohort size (the flatness claim), staleness p50/p99 and the buffer's
  # high-water depth — so the watcher log answers "is round throughput
  # still cohort-independent" without opening BENCH_MEASURED_*.json
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local asy
  asy=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
rph = doc.get("async_rounds_per_hr") or {}
if rph:
    rates = ", ".join(f"{k}: {v}/hr" for k, v in rph.items())
    p50 = doc.get("async_staleness_p50") or {}
    p99 = doc.get("async_staleness_p99") or {}
    hw = doc.get("async_buffer_high_water") or {}
    big = max(rph, key=lambda k: int(k))
    print(f"async_rounds (publish_k={doc.get('async_publish_k')}): {{{rates}}}, "
          f"flatness {doc.get('async_flatness_ratio')}, "
          f"staleness p50/p99@{big} {p50.get(big)}/{p99.get(big)}, "
          f"high_water {hw.get(big)}, "
          f"parity_bit_exact={doc.get('async_parity_bit_exact')}")
PYEOF
) || return 0
  [ -n "$asy" ] && log "$asy"
}

surface_wan_profile() {
  # one-line view of the per-link WAN observability stage: worst measured-
  # vs-injected bandwidth error across the throttled fleet and the probe
  # overhead share — so the watcher log answers "can the link estimators
  # still recover a known WAN profile, and for free" without opening
  # BENCH_MEASURED_*.json
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local wan
  wan=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
links = doc.get("wan_profile") or {}
if links:
    pairs = ", ".join(
        f"0->{r}: {v['measured_bytes_per_sec'] / 1e6:.2f}MB/s "
        f"({v['bw_error_pct']}% err)" for r, v in sorted(links.items()))
    print(f"wan_profile: {{{pairs}}}, "
          f"link_bw_error_pct {doc.get('link_bw_error_pct')}, "
          f"probe_overhead_pct {doc.get('probe_overhead_pct')}, "
          f"answered {doc.get('wan_probes_answered')}/{doc.get('wan_probes_sent')}")
PYEOF
) || return 0
  [ -n "$wan" ] && log "$wan"
}

surface_pipeline_overlap() {
  # one-line view of the pipelined round-execution stage: measured overlap
  # fraction, pipelined-vs-serial speedup and the planner's micro-batch
  # pick — so the watcher log answers "is uplink still hiding under
  # compute" without opening BENCH_MEASURED_*.json
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local pipe
  pipe=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("pipeline_overlap_frac") is not None:
    print(f"pipeline_overlap: frac {doc['pipeline_overlap_frac']} "
          f"(min {doc.get('pipeline_overlap_frac_min')}), "
          f"speedup {doc.get('pipeline_speedup')}x "
          f"({doc.get('pipeline_serial_wall_s')}s -> {doc.get('pipeline_wall_s')}s), "
          f"m={doc.get('pipeline_micro_batches')} "
          f"[{doc.get('pipeline_plan_reason')}], "
          f"bottleneck {doc.get('pipeline_bottleneck')}")
PYEOF
) || return 0
  [ -n "$pipe" ] && log "$pipe"
}

surface_devperf() {
  # one-line view of the devperf stage: the live registry's MFU vs bench's
  # analytic MFU (parity is integrity-guarded in-stage) plus the registry's
  # self-accounted overhead share — so the watcher log answers "is the
  # always-on device-perf layer still honest and still free" without
  # opening BENCH_MEASURED_*.json
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local dp
  dp=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("llm_mfu") is not None:
    print(f"devperf: llm_mfu {doc['llm_mfu']} "
          f"(analytic {doc.get('llm_mfu_analytic')}, "
          f"rel_err {doc.get('llm_mfu_rel_err')}), "
          f"overhead {doc.get('devperf_overhead_pct')}% of wall, "
          f"{doc.get('devperf_roofline_verdict')} "
          f"[{doc.get('devperf_flops_source')}], "
          f"hbm_samples {doc.get('devperf_hbm_samples')}")
PYEOF
) || return 0
  [ -n "$dp" ] && log "$dp"
}

surface_modelwatch() {
  # one-line view of the modelwatch stage: the fold-boundary stats' cost
  # share of a round-shaped loop (watched-vs-plain, integrity-guarded
  # in-stage) plus the detection liveness count — so the watcher log
  # answers "is training-dynamics observability still free and still
  # catching divergent clients" without opening BENCH_MEASURED_*.json
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local mw
  mw=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("modelwatch_overhead_pct") is not None:
    print(f"modelwatch: overhead {doc['modelwatch_overhead_pct']}% of round "
          f"(plain {doc.get('modelwatch_plain_round_ms')}ms vs watched "
          f"{doc.get('modelwatch_watched_round_ms')}ms, fold "
          f"{doc.get('modelwatch_fold_ms')}ms), detection "
          f"{doc.get('modelwatch_detection_caught')}/2 caught")
PYEOF
) || return 0
  [ -n "$mw" ] && log "$mw"
}

surface_secagg() {
  # one-line view of the secagg_overhead stage: the masking+DP fold's cost
  # share of a round-shaped loop (masked-vs-plain, integrity-guarded
  # in-stage, incl. bit-exact unmask parity) plus the accountant's spent
  # epsilon — so the watcher log answers "is the privacy subsystem still
  # ~free and still accounted" without opening BENCH_MEASURED_*.json
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local sa
  sa=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("secagg_overhead_pct") is not None:
    print(f"secagg: overhead {doc['secagg_overhead_pct']}% of round "
          f"(plain {doc.get('secagg_plain_round_ms')}ms vs masked+dp "
          f"{doc.get('secagg_masked_round_ms')}ms, d="
          f"{doc.get('secagg_model_dim')}), eps_spent "
          f"{doc.get('dp_epsilon_spent')} at z={doc.get('dp_noise_multiplier')}")
PYEOF
) || return 0
  [ -n "$sa" ] && log "$sa"
}

surface_fleet_scale() {
  # one-line view of the fleet_scale stage: sketch quantile accuracy vs
  # numpy exact, amortized telemetry bytes per client, and the ingest
  # overhead share of the driver slice (all integrity-guarded in-stage) —
  # so the watcher log answers "is million-client telemetry still accurate
  # and still O(nodes)" without opening BENCH_MEASURED_*.json
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local fs
  fs=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("fleet_scale_quantile_err_pct") is not None:
    print(f"fleet_scale: {doc.get('fleet_scale_clients')} clients, "
          f"quantile_err {doc['fleet_scale_quantile_err_pct']}% vs exact, "
          f"{doc.get('fleet_telemetry_bytes_per_client')}B/client across "
          f"{doc.get('fleet_scale_nodes')} nodes, ingest "
          f"{doc.get('fleet_scale_ingest_overhead_pct')}% of driver wall, "
          f"offenders {doc.get('fleet_scale_offenders_recovered')}, "
          f"edge==flat {doc.get('fleet_scale_edge_eq_flat')}")
PYEOF
) || return 0
  [ -n "$fs" ] && log "$fs"
}

surface_placement() {
  # one-line view of the auto-placement search: searched-vs-default speedup
  # per workload plus the winning candidate's knobs and fingerprint — so the
  # watcher log answers "did the search beat the hand-picked config, and
  # with what placement" without opening BENCH_MEASURED_*.json
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local plc
  plc=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
speed = doc.get("placement_speedup") or {}
plans = doc.get("placement_plan") or {}
if speed:
    parts = []
    for w, s in sorted(speed.items()):
        p = plans.get(w) or {}
        knobs = p.get("strategy", "?")
        if p.get("publish_k") is not None:
            knobs += f" k={p['publish_k']}/exp={p['staleness_exponent']}"
        parts.append(f"{w} {s}x ({knobs}, {p.get('fingerprint')})")
    print("placement_search: " + "; ".join(parts)
          + f"; plans: {', '.join(doc.get('placement_plan_files') or [])}")
PYEOF
) || return 0
  [ -n "$plc" ] && log "$plc"
}

surface_resilience() {
  # one-line view of the resilience rider on the agg stage: async round-
  # checkpoint enqueue cost and whether watermark resume round-tripped
  # bit-identically (resume_verified), so the watcher log answers "is
  # crash-resume still free and correct" per artifact
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local res
  res=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
if "resume_verified" in doc:
    print(f"resilience: ckpt_enqueue {doc.get('ckpt_enqueue_ms')}ms, "
          f"resume_verified={doc['resume_verified']}")
PYEOF
) || return 0
  [ -n "$res" ] && log "$res"
}

surface_serving() {
  # one-line view of the serving-perf keys in the newest artifact: the int8
  # decode speedup (the r05 regression this round fixed), the continuous-
  # batching load test's tokens/s + TTFT/TPOT tails, and slot occupancy —
  # so the watcher log answers "is the endpoint keeping the chip busy"
  # without opening BENCH_MEASURED_*.json
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local serving
  serving=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
parts = []
if doc.get("int8_decode_speedup") is not None:
    parts.append(f"int8_decode_speedup {doc['int8_decode_speedup']}x")
if doc.get("serving_load_tokens_per_sec") is not None:
    parts.append(
        f"serving_load {doc['serving_load_tokens_per_sec']} tok/s "
        f"@{doc.get('serving_load_streams')} streams "
        f"(ttft p50/p99 {doc.get('serving_load_ttft_p50_s')}/"
        f"{doc.get('serving_load_p99_ttft_s', doc.get('serving_load_ttft_p99_s'))}s, "
        f"tpot p99 {doc.get('serving_load_p99_tpot_s', doc.get('serving_load_tpot_p99_s'))}s, "
        f"occupancy peak {doc.get('serving_load_slot_occupancy_peak')} "
        f"mean {doc.get('serving_load_slot_occupancy_mean')})")
if doc.get("kv_pages_per_token") is not None:
    # paged-vs-fixed verdict: both claims in one line (tails + HBM)
    parts.append(
        f"paged KV: {doc['kv_pages_per_token']} pages/token, "
        f"hbm_ratio {doc.get('serving_load_kv_hbm_ratio')} "
        f"(paged ttft p99 {doc.get('serving_load_p99_ttft_s')}s vs "
        f"fixed {doc.get('serving_load_fixed_ttft_p99_s')}s, "
        f"prefix hits {doc.get('serving_load_prefix_hits')}/"
        f"{doc.get('serving_load_prefix_hits', 0) and (doc.get('serving_load_prefix_hits') or 0) + (doc.get('serving_load_prefix_misses') or 0)})")
if doc.get("serving_load_vs_decode") is not None:
    parts.append(f"vs raw decode {doc['serving_load_vs_decode']}x slower")
if parts:
    print("serving: " + "; ".join(parts))
PYEOF
) || return 0
  [ -n "$serving" ] && log "$serving"
}

surface_span_summary() {
  # one-line roll-up of the telemetry span stats riding the newest artifact
  # (agg_span_summary: count/total_ms/max_ms per agg.* span), so the watcher
  # log answers "where did the aggregation wall time go" per round
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local spans
  spans=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
stats = doc.get("agg_span_summary") or {}
if stats:
    parts = [f"{name} x{st['count']} {st['total_ms']:.0f}ms (max {st['max_ms']:.1f}ms)"
             for name, st in sorted(stats.items())]
    print("agg spans: " + "; ".join(parts))
PYEOF
) || return 0
  [ -n "$spans" ] && log "$spans"
}

surface_alerts() {
  # one-line view of the SLO evaluator keys riding the newest artifact
  # (alerts_fired + slo_overhead_pct from bench.py's slo_overhead rider), so
  # the watcher log answers "did any burn-rate alert fire during the
  # measurement, and what did evaluating cost" without opening the JSON
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local alerts
  alerts=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
if doc.get("alerts_fired") is not None or doc.get("slo_overhead_pct") is not None:
    print(f"slo: alerts_fired {doc.get('alerts_fired')}, "
          f"overhead {doc.get('slo_overhead_pct')}% of stage wall "
          f"(ticks {doc.get('slo_ticks')})")
PYEOF
) || return 0
  [ -n "$alerts" ] && log "$alerts"
}

surface_bench_regress() {
  # regression sentinel over the banked trajectory: compares each headline
  # key's newest occurrence against its prior occurrence / r0 baseline and
  # logs the verdict, so a decaying rounds/hr or a TTFT tail doubling is
  # called out the moment the artifact that shows it is committed
  local verdict rc
  verdict=$(timeout 60 python tools/bench_regress.py 2>/dev/null)
  rc=$?
  if [ $rc -eq 1 ]; then
    log "BENCH REGRESSION: $(echo "$verdict" | grep -E 'REGRESS|=>' | tr '\n' ' ')"
  elif [ $rc -eq 0 ] && [ -n "$verdict" ]; then
    log "bench_regress: $(echo "$verdict" | tail -1 | sed 's/^ *//')"
  else
    log "bench_regress: could not run (rc=$rc)"
  fi
}

surface_trace_files() {
  # surface where the trace artifacts landed (per-stage --trace Perfetto
  # file and the cross-silo fleet trace, if either stage produced one), so
  # the operator can pull them into ui.perfetto.dev without digging through
  # the artifact JSON
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1) || return 0
  [ -n "$newest" ] || return 0
  local traces
  traces=$(python3 - "$newest" <<'PYEOF' 2>/dev/null
import json, sys
doc = json.load(open(sys.argv[1]))
found = []
def walk(d):
    if isinstance(d, dict):
        for k, v in d.items():
            if k in ("trace_file", "fleet_trace_file") and isinstance(v, str):
                found.append(f"{k}={v}")
            else:
                walk(v)
walk(doc)
if found:
    print("trace files (open in ui.perfetto.dev): " + "; ".join(sorted(set(found))))
PYEOF
) || return 0
  [ -n "$traces" ] && log "$traces"
}

surface_crash_dumps() {
  # surface flight-recorder crash dumps: any "crash_dump" key riding the
  # newest artifact JSON plus fresh files in the recorder's dump dir, so a
  # stage that died mid-measurement points straight at its forensic record
  # (render with: python tools/fr_dump.py PATH)
  local newest
  newest=$(ls -1t BENCH_MEASURED_*.json 2>/dev/null | head -1)
  local dumps
  dumps=$(python3 - "${newest:-}" <<'PYEOF' 2>/dev/null
import glob, json, os, sys, time
found = []
if len(sys.argv) > 1 and sys.argv[1] and os.path.exists(sys.argv[1]):
    doc = json.load(open(sys.argv[1]))
    def walk(d):
        if isinstance(d, dict):
            for k, v in d.items():
                if k == "crash_dump" and isinstance(v, str):
                    found.append(v)
                else:
                    walk(v)
    walk(doc)
dump_dir = os.environ.get("FEDML_FR_DIR") or os.path.expanduser("~/.fedml_tpu/crash")
cutoff = time.time() - 24 * 3600
for p in glob.glob(os.path.join(dump_dir, "fr_*.jsonl")):
    if os.path.getmtime(p) >= cutoff:
        found.append(p)
if found:
    print("crash dumps (render: python tools/fr_dump.py PATH): "
          + "; ".join(sorted(set(found))))
PYEOF
) || return 0
  [ -n "$dumps" ] && log "$dumps"
}

have_measured_headline() {
  # true iff some measured artifact carries a NUMERIC headline value — the
  # full ladder writes incremental artifacts even when the headline stage
  # died, and mere file existence must not disable the short-window path
  # before a headline ever landed
  grep -l '"value": [0-9]' BENCH_MEASURED_*.json >/dev/null 2>&1
}

surface_fedlint

while true; do
  # tpu_probe.py EXECUTES a jitted op (shared with bench.py's _probe_backend
  # — one definition): jax.devices() alone only proves the tunnel's control
  # plane, and windows exist where metadata answers while every
  # compile/execute RPC stalls (2026-07-31: a whole bench run of stage
  # timeouts behind a "green" devices() probe).
  # flock -n: the probe (and the smoke below) touch the chip, so they stand
  # down while a driver-run bench holds the lock — only bench.py itself
  # manages the lock internally (it must, for the yield/preempt protocol)
  if timeout "$PROBE_TIMEOUT" flock -n "$LOCK" python tools/tpu_probe.py >/dev/null 2>&1; then
    # FIRST: the short-window fast path (VERDICT r4 weak #2) — probe + one
    # fast pallas headline stage + commit, sized to land a number inside a
    # ~3-minute window. Only until a measured HEADLINE exists (a headline-
    # less incremental artifact from a half-dead ladder doesn't count):
    # after that, windows go straight to smoke + the full ladder.
    if ! have_measured_headline; then
      log "tunnel up — running short-window bench first (no measured headline banked yet)"
      if timeout 330 env FEDML_BENCH_WATCHER=1 python bench.py --short-window >/tmp/bench_short_last.json 2>/tmp/bench_short_last.err; then
        log "short-window headline landed: $(cat /tmp/bench_short_last.json)"
      else
        log "short-window bench incomplete: $(tail -c 300 /tmp/bench_short_last.err)"
      fi
      commit_artifacts
    fi
    if [ ! -f "$SMOKE_STAMP" ]; then
      log "tunnel up — running pallas TPU smoke"
      if timeout "$SMOKE_TIMEOUT" flock -n "$LOCK" python tools/tpu_smoke_flash.py >/tmp/smoke_tpu.log 2>&1; then
        log "smoke PASS: $(tail -3 /tmp/smoke_tpu.log | tr '\n' ' ')"
        cp /tmp/smoke_tpu.log "$REPO/docs/tpu_smoke_flash.log" 2>/dev/null || true
        git add docs/tpu_smoke_flash.log 2>/dev/null && \
          git commit -q -m "Record pallas flash-attention TPU smoke (fwd+bwd parity on real Mosaic)" -- docs/tpu_smoke_flash.log 2>/dev/null || true
        echo "$KERNEL_HASH" > "$SMOKE_STAMP"
      else
        log "smoke FAILED/timeout: $(tail -3 /tmp/smoke_tpu.log | tr '\n' ' ')"
        # don't stamp: retry next window — but continue to the bench anyway
        # (its pallas stage has its own xla fallback)
      fi
    fi
    log "running bench.py"
    # FEDML_BENCH_WATCHER: this instance YIELDS the chip to a driver-run
    # bench (structured bench_lock_held skip) instead of contending with it
    if timeout "$BENCH_TIMEOUT" env FEDML_BENCH_WATCHER=1 python bench.py >/tmp/bench_watch_last.json 2>/tmp/bench_watch_last.err; then
      log "bench ok: $(cat /tmp/bench_watch_last.json)"
      commit_artifacts
      sleep "$SLEEP_UP"
    else
      rc=$?
      if grep -q '"skipped": *"tunnel_stalled"' /tmp/bench_watch_last.json 2>/dev/null; then
        log "tunnel stalled mid-run (structured skip, rc=$rc)"
      elif grep -q '"skipped": *"bench_lock_held"' /tmp/bench_watch_last.json 2>/dev/null; then
        log "another bench owns the chip (designed yield, rc=$rc)"
      else
        log "bench incomplete (rc=$rc): $(tail -c 400 /tmp/bench_watch_last.err)"
        # a dying stage may have left a crash dump even when no artifact
        # landed — surface it now rather than only on successful commits
        surface_crash_dumps
      fi
      # stage isolation means partial artifacts may still exist — bank them
      commit_artifacts
      sleep "$SLEEP_DOWN"
    fi
  else
    log "tunnel down"
    sleep "$SLEEP_DOWN"
  fi
done
