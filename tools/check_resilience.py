#!/usr/bin/env python3
"""Resilience-idiom lint: no ad-hoc retry loops, no bypassing the watermark.

Two rules enforced over every ``fedml_tpu/**/*.py`` file:

1. **No bare sleep loops.** A line containing ``time.sleep(`` outside
   ``core/resilience/retry.py`` must carry a ``# sleep ok: <reason>`` marker
   on the same line. Hand-rolled ``for attempt in range(n): ... sleep(...)``
   loops are how unbounded, untelemetered retries creep back in — transient
   failures belong to :mod:`fedml_tpu.core.resilience.retry` (jittered,
   budget-capped, flight-recorder-booked). The marker is the allowlist for
   sleeps that are *not* retries: chaos injection, polling an external
   process, rate pacing — the reason says which.

2. **Checkpoint writes go through the watermark.** Orbax checkpointers
   (``ocp.CheckpointManager`` / ``orbax.checkpoint``) may only be touched by
   ``fedml_tpu/utils/checkpoint.py``. Everything else uses
   :class:`fedml_tpu.utils.checkpoint.CheckpointManager`, whose async save +
   watermark commit is what makes crash-resume pick a *complete* step; a
   direct orbax save would reintroduce torn checkpoints.

Anything unmarked fails tier-1 (tests/test_resilience.py invokes ``main()``).
Exit status: 0 clean, 1 with violations listed on stdout.
"""

from __future__ import annotations

import os
import sys

SLEEP_MARKER = "sleep ok"
SLEEP_PATTERN = "time.sleep("
SLEEP_EXEMPT = os.path.join("core", "resilience", "retry.py")

ORBAX_PATTERNS = ("ocp.CheckpointManager", "orbax.checkpoint")
ORBAX_HOME = os.path.join("utils", "checkpoint.py")


def find_violations(root: str) -> list:
    violations = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if (
                        SLEEP_PATTERN in line
                        and SLEEP_MARKER not in line
                        and not rel.endswith(SLEEP_EXEMPT)
                    ):
                        violations.append((path, lineno, "unmarked time.sleep()", line.strip()))
                    if (
                        any(p in line for p in ORBAX_PATTERNS)
                        and not rel.endswith(ORBAX_HOME)
                    ):
                        violations.append((path, lineno, "orbax outside utils/checkpoint.py", line.strip()))
    return violations


def main(argv: list = ()) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = argv[0] if argv else os.path.join(repo, "fedml_tpu")
    violations = find_violations(root)
    for path, lineno, kind, line in violations:
        print(f"{os.path.relpath(path, repo)}:{lineno}: {kind}: {line}")
    if violations:
        print(
            f"\n{len(violations)} resilience violation(s). Retries belong to "
            "fedml_tpu.core.resilience.retry (jittered, budget-capped); checkpoint "
            "writes go through fedml_tpu.utils.checkpoint (watermark commit); "
            f"legitimate non-retry sleeps need a '# {SLEEP_MARKER}: <reason>' marker."
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
