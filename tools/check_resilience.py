#!/usr/bin/env python3
"""Resilience-idiom lint — thin shim over ``tools.fedlint`` (rules:
bare-sleep, orbax).

The walker that lived here (PR 5) is now
``tools/fedlint/rules/resilience.py``; this shim preserves the historical
contract — ``find_violations(root)`` tuples, stdout format, exit codes —
for tier-1 callers (tests/test_resilience.py). New callers use
``python -m tools.fedlint``.

Rules: ``time.sleep()`` outside ``core/resilience/retry.py`` needs a
``# fedlint: disable=bare-sleep <reason>`` suppression (legacy
``# sleep ok:`` still honored); orbax checkpointers are touched only by
``fedml_tpu/utils/checkpoint.py`` (watermark commit).
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.fedlint import api  # noqa: E402

SLEEP_MARKER = "sleep ok"

_KINDS = {
    "bare-sleep": "unmarked time.sleep()",
    "orbax": "orbax outside utils/checkpoint.py",
}


def find_violations(root: str) -> list:
    """Legacy shape: (path, lineno, kind, stripped source line)."""
    result = api.run_rules(root, ["bare-sleep", "orbax"])
    return [(f.path, f.line, _KINDS[f.rule], f.line_text.strip())
            for f in result.findings if f.rule in _KINDS]


def main(argv: list = ()) -> int:
    root = argv[0] if argv else os.path.join(_REPO, "fedml_tpu")
    violations = find_violations(root)
    for path, lineno, kind, line in violations:
        print(f"{os.path.relpath(path, _REPO)}:{lineno}: {kind}: {line}")
    if violations:
        print(
            f"\n{len(violations)} resilience violation(s). Retries belong to "
            "fedml_tpu.core.resilience.retry (jittered, budget-capped); checkpoint "
            "writes go through fedml_tpu.utils.checkpoint (watermark commit); "
            "legitimate non-retry sleeps need a "
            "'# fedlint: disable=bare-sleep <reason>' suppression."
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
