"""fedlint engine: one shared AST walk, a Rule plugin API, unified
suppressions, and a reviewed baseline.

Design (ISSUE 8):

* **One walk per file.** The engine parses each file once, builds a
  parent-link map and qualified-name/scope helpers (:class:`FileContext`),
  and dispatches every node to the rules subscribed to its type
  (``Rule.node_types``). Rules that need whole-module dataflow (donation
  tracking, lock protection maps) implement ``check_file`` instead and get
  the same parsed context. Tree-level rules (registries that must notice a
  *missing* file) implement ``finalize``.

* **Findings** carry rule id, severity, span (line/col), the offending
  source line, and a stable fingerprint (rule + relpath + normalized line
  text) so the baseline survives unrelated line drift.

* **Suppression** is ONE syntax everywhere::

      x = risky()  # fedlint: disable=rule-id[,rule-id] <reason>
      # fedlint: disable-file=rule-id[,rule-id] <reason>

  The pragma must sit on the reported line (file-level pragmas anywhere in
  the file). A pragma without a reason is itself reported
  (``bare-suppression``) — suppressions are reviewed artifacts, not mute
  buttons. Legacy markers (``# wall-clock ok:``, ``# sleep ok:``) are still
  honored by the two rules that introduced them so the ``check_*.py`` shims
  keep their historical contracts; new code uses the unified syntax.

* **Baseline**: a checked-in JSON file of grandfathered findings, every
  entry carrying a mandatory reason. Matching findings are reported as
  "baselined", not failures; stale entries (matching nothing) are reported
  so the baseline only ever shrinks.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

SEVERITIES = ("error", "warn")

# rule ids are kebab-case tokens; "all" is reserved for blanket pragmas
_PRAGMA_RE = re.compile(
    r"#\s*fedlint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\-]+)\s*(.*)$"
)

#: rule id used for suppression pragmas that carry no reason
BARE_SUPPRESSION = "bare-suppression"
#: rule id used for files the engine cannot parse
SYNTAX_ERROR = "syntax-error"


@dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str          # absolute
    relpath: str       # relative to the run root, '/'-separated
    line: int
    col: int
    message: str
    line_text: str = ""

    @property
    def fingerprint(self) -> str:
        basis = self.line_text.strip() or self.message
        h = hashlib.sha1(
            f"{self.rule}|{self.relpath}|{basis}".encode("utf-8", "replace")
        )
        return h.hexdigest()[:16]

    def render(self) -> str:
        loc = f"{self.relpath}:{self.line}"
        if self.col:
            loc += f":{self.col}"
        return f"{loc}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.relpath,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "line_text": self.line_text.strip(),
            "fingerprint": self.fingerprint,
        }


class _Suppressions:
    """Per-file pragma table, parsed from real COMMENT tokens (so pragma
    examples inside docstrings never count)."""

    def __init__(self):
        self.by_line: dict = {}      # lineno -> set of rule ids (or {"all"})
        self.file_wide: set = set()
        self.bare_lines: list = []   # linenos of reason-less pragmas

    @classmethod
    def scan(cls, source: str) -> "_Suppressions":
        sup = cls()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = [(i, line) for i, line in
                        enumerate(source.splitlines(), 1) if "#" in line]
        for lineno, text in comments:
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            kind, rules_s, reason = m.groups()
            rules = {r.strip() for r in rules_s.split(",") if r.strip()}
            if not reason.strip():
                sup.bare_lines.append(lineno)
            if kind == "disable-file":
                sup.file_wide |= rules
            else:
                sup.by_line.setdefault(lineno, set()).update(rules)
        return sup

    def matches(self, rule: str, line: int) -> bool:
        if rule in self.file_wide or "all" in self.file_wide:
            return True
        at = self.by_line.get(line, ())
        return rule in at or "all" in at

    # --- cache serialization (tools.fedlint.project) ---------------------
    def to_json(self) -> dict:
        return {
            "by_line": {str(k): sorted(v) for k, v in self.by_line.items()},
            "file_wide": sorted(self.file_wide),
            "bare_lines": list(self.bare_lines),
        }

    @classmethod
    def from_json(cls, doc: dict) -> "_Suppressions":
        sup = cls()
        sup.by_line = {int(k): set(v)
                       for k, v in (doc.get("by_line") or {}).items()}
        sup.file_wide = set(doc.get("file_wide") or ())
        sup.bare_lines = list(doc.get("bare_lines") or ())
        return sup


class FileContext:
    """Everything a rule may ask about one parsed file."""

    def __init__(self, root: str, path: str, source: str, tree: ast.AST):
        self.root = root
        self.path = path
        self.relpath = os.path.relpath(path, root).replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents: dict = {}
        self.suppressions = _Suppressions.scan(source)
        self._qualname_cache: dict = {}

    # --- source access ---------------------------------------------------
    def raw_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    # --- structure helpers ------------------------------------------------
    def parent(self, node: ast.AST):
        return self.parents.get(node)

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def enclosing_class(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
        return None

    def in_loop(self, node: ast.AST) -> bool:
        """True when ``node`` sits inside a for/while loop without crossing a
        function boundary (a nested def resets hotness)."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                cur = self.parents.get(cur)
                continue
            cur = self.parents.get(cur)
        return False

    def in_loop_strict(self, node: ast.AST) -> bool:
        """Like :meth:`in_loop` but a function boundary stops the search —
        code inside a nested helper def is that helper's business."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
            cur = self.parents.get(cur)
        return False

    def qualname(self, node: ast.AST) -> str:
        """Dotted scope name: ``Class.method.<locals>.inner`` style without
        the ``<locals>`` noise — ``Class.method.inner``."""
        if node in self._qualname_cache:
            return self._qualname_cache[node]
        parts = []
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            parts.append(node.name)
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                parts.append(anc.name)
        qn = ".".join(reversed(parts))
        self._qualname_cache[node] = qn
        return qn


class Rule:
    """Plugin base. Subclasses set ``id``/``severity``/``description`` and
    implement one (or more) of:

    * ``node_types`` + ``check_node(node, ctx)`` — per-node subscription on
      the shared walk;
    * ``check_file(ctx)`` — whole-module analyses (run after the walk, so
      ``ctx.parents`` is complete);
    * ``finalize(run)`` — tree-level checks after every file (missing-file
      registries).

    All three yield/return iterables of :class:`Finding`; use
    :meth:`make` to build them consistently.
    """

    id: str = ""
    severity: str = "error"
    description: str = ""
    node_types: tuple = ()

    def configure(self, options: dict) -> None:
        """Hook for [tool.fedlint] per-rule options; default ignores them."""

    def applies_to(self, relpath: str) -> bool:
        return True

    def check_node(self, node: ast.AST, ctx: FileContext):
        return ()

    def check_file(self, ctx: FileContext):
        return ()

    def finalize(self, run: "RunContext"):
        return ()

    def make(self, ctx: FileContext, node, message: str) -> Finding:
        if isinstance(node, int):
            line, col = node, 0
        else:
            line = getattr(node, "lineno", 0)
            col = getattr(node, "col_offset", 0)
        return Finding(
            rule=self.id, severity=self.severity, path=ctx.path,
            relpath=ctx.relpath, line=line, col=col, message=message,
            line_text=ctx.raw_line(line),
        )


class ProjectRule(Rule):
    """Whole-program rule: per-file fact collection + a finalize pass over
    the :class:`tools.fedlint.project.ProjectGraph`.

    The split is what makes these rules cacheable: ``collect(ctx)`` runs
    only on files that changed (returning a JSON-serializable fact dict
    that is stored in the incremental cache), while
    ``finalize_project(graph, facts)`` runs every time over the union of
    fresh and cached facts. Facts must therefore carry everything a
    finding needs — line numbers and line text included — because at
    finalize time there is no live :class:`FileContext` for cache-hit
    files.
    """

    #: marks the rule for the project engine's collect/finalize protocol
    project = True

    def collect(self, ctx: FileContext):
        """Per-file facts (JSON-safe dict) or None when the file holds
        nothing of interest. Runs only on changed files."""
        return None

    def finalize_project(self, graph, facts: dict):
        """Cross-file findings from ``facts`` (relpath -> collect() result,
        interest-bearing files only) and the project ``graph``."""
        return ()

    def fact_finding(self, root: str, relpath: str, line: int, message: str,
                     line_text: str = "", severity: str = None) -> Finding:
        """Build a Finding without a live FileContext (cache-hit files)."""
        return Finding(
            rule=self.id, severity=severity or self.severity,
            path=os.path.join(root, *relpath.split("/")), relpath=relpath,
            line=line, col=0, message=message, line_text=line_text)


@dataclass
class RunContext:
    root: str
    files: list = field(default_factory=list)       # FileContext, parse OK
    failed: list = field(default_factory=list)      # (path, SyntaxError)

    def relpaths(self) -> set:
        return {ctx.relpath for ctx in self.files}


@dataclass
class RunResult:
    findings: list = field(default_factory=list)     # live, unsuppressed
    suppressed: list = field(default_factory=list)   # (finding, "pragma")
    baselined: list = field(default_factory=list)
    stale_baseline: list = field(default_factory=list)  # baseline entries matching nothing
    files_scanned: int = 0
    # project-engine extras (tools.fedlint.project): which files were
    # actually parsed this run vs served from the incremental cache
    analyzed: list = field(default_factory=list)         # relpaths parsed
    cache_hits: int = 0
    wall_time_s: float = 0.0

    @property
    def files_analyzed(self) -> int:
        return len(self.analyzed)

    @property
    def cache_hit_rate(self) -> float:
        total = self.files_analyzed + self.cache_hits
        return self.cache_hits / total if total else 0.0

    @property
    def errors(self):
        return [f for f in self.findings if f.severity == "error"]

    def exit_code(self) -> int:
        return 1 if self.errors else 0

    def to_json(self) -> dict:
        return {
            "files_scanned": self.files_scanned,
            "files_analyzed": self.files_analyzed,
            "analyzed": sorted(self.analyzed),
            "cache_hits": self.cache_hits,
            "cache_hit_rate": round(self.cache_hit_rate, 4),
            "wall_time_s": round(self.wall_time_s, 3),
            "counts": {
                "findings": len(self.findings),
                "errors": len(self.errors),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
                "stale_baseline": len(self.stale_baseline),
            },
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [f.to_json() for f in self.suppressed],
            "baselined": [f.to_json() for f in self.baselined],
            "stale_baseline": list(self.stale_baseline),
        }


# --- file discovery ---------------------------------------------------------

def iter_py_files(root: str, paths, exclude):
    """Yield absolute paths of .py files under ``paths`` (files or dirs,
    relative to ``root``), pruning any directory whose name or root-relative
    path is in ``exclude``."""
    exclude = set(exclude or ())
    seen = set()
    for p in paths:
        top = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(top):
            if top not in seen:
                seen.add(top)
                yield top
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            rel_dir = os.path.relpath(dirpath, root).replace(os.sep, "/")
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in exclude
                and f"{rel_dir}/{d}".lstrip("./") not in exclude
                and not d.startswith(".")
            )
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                if path not in seen:
                    seen.add(path)
                    yield path


# --- the shared walk --------------------------------------------------------

def _walk_and_dispatch(ctx: FileContext, dispatch: dict, sink: list):
    """Single DFS: record parent links and hand each node to the rules
    subscribed to its type."""
    stack = [ctx.tree]
    while stack:
        node = stack.pop()
        for rule in dispatch.get(type(node), ()):
            sink.extend(
                (rule, f) for f in (rule.check_node(node, ctx) or ())
            )
        children = list(ast.iter_child_nodes(node))
        for child in children:
            ctx.parents[child] = node
        stack.extend(reversed(children))


def run(root: str, paths, rules, exclude=(), baseline_entries=()) -> RunResult:
    """Run ``rules`` over every .py under ``paths``; returns a
    :class:`RunResult` with pragma-suppression and baseline applied.

    ``baseline_entries`` is an iterable of dicts with ``rule``, ``path``,
    ``fingerprint`` (see :mod:`tools.fedlint.baseline`).
    """
    root = os.path.abspath(root)
    runctx = RunContext(root=root)
    result = RunResult()

    dispatch: dict = {}
    for rule in rules:
        for nt in rule.node_types:
            dispatch.setdefault(nt, []).append(rule)

    raw: list = []  # (rule_obj_or_None, Finding)

    for path in iter_py_files(root, paths, exclude):
        result.files_scanned += 1
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            relpath = os.path.relpath(path, root).replace(os.sep, "/")
            result.findings.append(Finding(
                rule=SYNTAX_ERROR, severity="error", path=path,
                relpath=relpath, line=e.lineno or 0, col=e.offset or 0,
                message=f"unparseable: {e.msg}"))
            runctx.failed.append((path, e))
            continue
        ctx = FileContext(root, path, source, tree)
        runctx.files.append(ctx)

        active = [r for r in rules if r.applies_to(ctx.relpath)]
        for rule in active:
            begin = getattr(rule, "begin_file", None)
            if begin is not None:
                begin(ctx)
        file_dispatch = {
            nt: [r for r in rs if r in active] for nt, rs in dispatch.items()
        }
        _walk_and_dispatch(ctx, file_dispatch, raw)
        for rule in active:
            raw.extend((rule, f) for f in (rule.check_file(ctx) or ()))

        for lineno in ctx.suppressions.bare_lines:
            raw.append((None, Finding(
                rule=BARE_SUPPRESSION, severity="error", path=path,
                relpath=ctx.relpath, line=lineno, col=0,
                message="suppression pragma without a reason — write "
                        "`# fedlint: disable=<rule> <why it is safe>`",
                line_text=ctx.raw_line(lineno))))

    for rule in rules:
        for f in rule.finalize(runctx) or ():
            raw.append((rule, f))

    # --- suppression + baseline filters ---
    by_ctx = {ctx.path: ctx for ctx in runctx.files}
    baseline_keys = {}
    for e in baseline_entries or ():
        baseline_keys.setdefault(
            (e.get("rule"), e.get("path"), e.get("fingerprint")), []).append(e)
    matched_baseline = set()

    for rule, finding in raw:
        ctx = by_ctx.get(finding.path)
        if (ctx is not None
                and finding.rule != BARE_SUPPRESSION
                and ctx.suppressions.matches(finding.rule, finding.line)):
            result.suppressed.append(finding)
            continue
        key = (finding.rule, finding.relpath, finding.fingerprint)
        if key in baseline_keys:
            matched_baseline.add(key)
            result.baselined.append(finding)
            continue
        result.findings.append(finding)

    for key, entries in baseline_keys.items():
        if key not in matched_baseline:
            result.stale_baseline.extend(entries)

    result.analyzed = [ctx.relpath for ctx in runctx.files] + [
        os.path.relpath(p, root).replace(os.sep, "/") for p, _e in runctx.failed]
    result.findings.sort(key=lambda f: (f.relpath, f.line, f.rule))
    return result
