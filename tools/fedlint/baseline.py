"""Baseline file: grandfathered findings, each with a mandatory reason.

The baseline exists so a new rule can land tree-wide without a flag-day —
pre-existing findings get parked here (reviewed, reasoned) and burned down
over time. Two invariants, both enforced at load/write time:

* every entry carries a non-empty ``reason`` (ISSUE 8: "no entry may land
  in the baseline file without a reason string");
* stale entries (matching no current finding) are surfaced by the CLI so
  the file only ever shrinks.
"""

from __future__ import annotations

import json
import os


class BaselineError(ValueError):
    pass


def load(path: str) -> list:
    """Entries from ``path``; [] when the file does not exist."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("entries", [])
    for e in entries:
        for field in ("rule", "path", "fingerprint"):
            if not e.get(field):
                raise BaselineError(
                    f"baseline entry missing '{field}': {e!r}")
        if not str(e.get("reason", "")).strip():
            raise BaselineError(
                f"baseline entry for {e['path']} [{e['rule']}] has no "
                "reason — every grandfathered finding must say why it is "
                "parked, not fixed")
    return entries


def write(path: str, findings, reason: str) -> int:
    """Write ``findings`` as the new baseline, all under one ``reason``."""
    if not reason or not reason.strip():
        raise BaselineError("--write-baseline requires --reason <text>")
    entries = [
        {
            "rule": f.rule,
            "path": f.relpath,
            "fingerprint": f.fingerprint,
            "line": f.line,
            "message": f.message,
            "reason": reason.strip(),
        }
        for f in findings
    ]
    payload = {"version": 1, "entries": entries}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return len(entries)
