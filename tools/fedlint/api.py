"""Programmatic entry points.

Two callers: the CLI (:mod:`tools.fedlint.cli`) and the legacy
``tools/check_*.py`` shims, which run a subset of rules over an arbitrary
root (their historical CLI contract lets tests point them at synthetic
trees) and adapt the findings to their historical tuple shapes.
"""

from __future__ import annotations

import os

from . import baseline as baseline_mod
from .config import load_config
from .core import RunResult, run
from .registry import all_rules, get_rules


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_rules(root: str, rule_ids, paths=None, exclude=(),
              options: dict = None) -> RunResult:
    """Run ``rule_ids`` over ``root`` (whole tree when ``paths`` is None).
    No baseline — shims and tests see raw (pragma-filtered) findings."""
    rules = get_rules(rule_ids, options=options or load_config(repo_root()))
    return run(root, paths or ["."], rules, exclude=exclude)


def run_repo(root: str = None, rule_ids=None, use_baseline: bool = True) -> RunResult:
    """The full configured run: config paths/excludes, every rule (minus
    config-disabled), baseline applied. This is what CI and the CLI use."""
    root = root or repo_root()
    cfg = load_config(root)
    rules = (get_rules(rule_ids, options=cfg) if rule_ids
             else [r for r in all_rules(cfg) if r.id not in set(cfg.get("disable") or ())])
    entries = []
    if use_baseline:
        entries = baseline_mod.load(os.path.join(root, cfg["baseline"]))
    return run(root, cfg["paths"], rules, exclude=cfg["exclude"],
               baseline_entries=entries)
