"""Programmatic entry points.

Two callers: the CLI (:mod:`tools.fedlint.cli`) and the legacy
``tools/check_*.py`` shims, which run a subset of rules over an arbitrary
root (their historical CLI contract lets tests point them at synthetic
trees) and adapt the findings to their historical tuple shapes.
"""

from __future__ import annotations

import os

from . import baseline as baseline_mod
from .config import load_config
from .core import RunResult
from .project import run_project
from .registry import all_rules, get_rules


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_rules(root: str, rule_ids, paths=None, exclude=(),
              options: dict = None) -> RunResult:
    """Run ``rule_ids`` over ``root`` (whole tree when ``paths`` is None).
    No baseline, no cache — shims and tests see raw (pragma-filtered)
    findings computed fresh every call."""
    rules = get_rules(rule_ids, options=options or load_config(repo_root()))
    return run_project(root, paths or ["."], rules, exclude=exclude,
                       cache_path=None)


def run_repo(root: str = None, rule_ids=None, use_baseline: bool = True,
             use_cache: bool = True, changed_scope=None) -> RunResult:
    """The full configured run: config paths/excludes, every rule (minus
    config-disabled), baseline applied, incremental cache warm. This is what
    CI, bench_watch, and the CLI use."""
    root = root or repo_root()
    cfg = load_config(root)
    rules = (get_rules(rule_ids, options=cfg) if rule_ids
             else [r for r in all_rules(cfg) if r.id not in set(cfg.get("disable") or ())])
    entries = []
    if use_baseline:
        entries = baseline_mod.load(os.path.join(root, cfg["baseline"]))
    cache_path = os.path.join(root, cfg["cache"]) if use_cache else None
    return run_project(root, cfg["paths"], rules, exclude=cfg["exclude"],
                       baseline_entries=entries, cache_path=cache_path,
                       changed_scope=changed_scope)
