"""Incremental-analysis cache for the project engine.

One JSON document per repo: ``{"sig": <engine signature>, "files":
{relpath: entry}}``. The signature hashes the rule set + summary format
version, so adding/removing a rule or changing the cache layout cold-starts
the whole cache instead of mixing incompatible entries.

Writes are atomic (temp file + ``os.replace``) so a crashed run never
leaves a truncated cache behind; a corrupt or unreadable cache is treated
as empty, never as an error — the cache is a pure accelerator.
"""

from __future__ import annotations

import json
import os
import tempfile

DEFAULT_CACHE_NAME = ".fedlint_cache.json"


def load(path: str, sig: str) -> dict:
    """Cached ``{relpath: entry}`` map, or ``{}`` when the cache is absent,
    unreadable, corrupt, or was written by a different engine signature."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict) or doc.get("sig") != sig:
        return {}
    files = doc.get("files")
    return files if isinstance(files, dict) else {}


def save(path: str, sig: str, files: dict) -> None:
    """Atomically persist the cache; failures are swallowed (a missing
    cache only costs the next run a cold start)."""
    doc = {"sig": sig, "files": files}
    directory = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd, tmp = tempfile.mkstemp(
            prefix=".fedlint_cache.", suffix=".tmp", dir=directory)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(doc, f, separators=(",", ":"))
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except OSError:
        pass
