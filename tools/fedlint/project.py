"""Project graph + incremental engine (ISSUE 10).

One pass over each parsed file produces a JSON-serializable **summary**:
the module's dotted name, its project-local import edges, a symbol table of
module/class string-and-int constants, the functions it defines, and every
call site (caller scope, dotted callee, line). Summaries — together with
each file's per-file rule findings, suppression table, and the per-rule
facts of every :class:`~tools.fedlint.core.ProjectRule` — live in a
content-hash cache (:mod:`tools.fedlint.cache`), so a warm run re-parses
nothing and still runs every whole-program rule over the full fact set.

Invalidation follows import edges: a changed file dirties itself plus its
reverse import closure (everything that transitively imports it), because
a file-scoped finding may depend on what it imports. Project rules are
immune to staleness by construction — their ``finalize_project`` runs
every time over fresh+cached facts.

Unparseable files are never cached (ISSUE 10 satellite: a syntax error
must not poison the cache) — they are re-analyzed each run and re-emit the
``syntax-error`` finding until they parse.
"""

from __future__ import annotations

import ast
import hashlib
import os
import time

from . import cache as cache_mod
from .core import (
    BARE_SUPPRESSION, SYNTAX_ERROR, FileContext, Finding, ProjectRule,
    RunContext, RunResult, _Suppressions, _walk_and_dispatch, iter_py_files,
)

#: bump when the summary/cache layout changes — stale layouts re-analyze
SUMMARY_VERSION = 1


# --- one-pass summary collection --------------------------------------------

def module_name(relpath: str) -> str:
    """Dotted module for a repo-relative path: ``a/b/c.py`` -> ``a.b.c``,
    ``a/b/__init__.py`` -> ``a.b``."""
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


def _resolve_relative(base_module: str, is_pkg: bool, level: int, target: str):
    """Absolute dotted module for ``from <level dots><target> import ...``."""
    parts = base_module.split(".")
    if not is_pkg:
        parts = parts[:-1]
    if level > 1:
        parts = parts[: len(parts) - (level - 1)]
        if len(parts) < 0:
            return None
    prefix = ".".join(parts)
    if target:
        return f"{prefix}.{target}" if prefix else target
    return prefix or None


def collect_summary(ctx: FileContext) -> dict:
    """The one-pass symbol table / import graph / call graph slice for one
    parsed file. Everything is JSON-safe for the incremental cache."""
    relpath = ctx.relpath
    mod = module_name(relpath)
    is_pkg = relpath.endswith("/__init__.py") or relpath == "__init__.py"

    # parent links are normally recorded by the dispatch walk, but this
    # function must also work on a freshly parsed FileContext (qualname and
    # the module-level checks below all need them)
    if not ctx.parents:
        for p in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(p):
                ctx.parents[child] = p

    imports: set = set()          # dotted modules this file depends on
    bindings: dict = {}           # local name -> "module" or "module:attr"
    constants: dict = {}          # "NAME" / "Class.NAME" -> str|int value + line
    functions: dict = {}          # qualname -> def line
    classes: dict = {}            # class name -> [method names]
    attr_types: dict = {}         # class -> {self attr -> ctor dotted name}
    calls: list = []              # [scope_qualname, dotted_callee, line]

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                imports.add(a.name)
                bindings[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            target = node.module or ""
            if node.level:
                target = _resolve_relative(mod, is_pkg, node.level, target)
                if target is None:
                    continue
            imports.add(target)
            for a in node.names:
                if a.name == "*":
                    continue
                # "from pkg import sub" may bind a module; record both forms
                bindings[a.asname or a.name] = f"{target}:{a.name}"
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            functions[ctx.qualname(node)] = node.lineno
        elif isinstance(node, ast.ClassDef):
            classes.setdefault(node.name, [])
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    classes[node.name].append(item.name)
                elif isinstance(item, ast.Assign):
                    val = item.value
                    if isinstance(val, ast.Constant) and isinstance(
                            val.value, (str, int)) and not isinstance(
                            val.value, bool):
                        for tgt in item.targets:
                            if isinstance(tgt, ast.Name):
                                constants[f"{node.name}.{tgt.id}"] = [
                                    val.value, item.lineno]
        elif isinstance(node, ast.Assign):
            # module-level constants only (class-level handled above)
            if ctx.parent(node) is ctx.tree and isinstance(
                    node.value, ast.Constant) and isinstance(
                    node.value.value, (str, int)) and not isinstance(
                    node.value.value, bool):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        constants[tgt.id] = [node.value.value, node.lineno]
            # self.attr = Ctor(...) — instance-attribute types, so rules can
            # resolve self.attr.method() calls across files
            elif isinstance(node.value, ast.Call):
                ctor = _dotted(node.value.func)
                if ctor:
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            cls = ctx.enclosing_class(node)
                            if cls is not None:
                                attr_types.setdefault(
                                    cls.name, {}).setdefault(tgt.attr, ctor)
        elif isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name:
                fn = ctx.enclosing_function(node)
                scope = ctx.qualname(fn) if fn is not None else ""
                calls.append([scope, name, node.lineno])

    return {
        "module": mod,
        "imports": sorted(imports),
        "bindings": bindings,
        "constants": constants,
        "functions": functions,
        "classes": classes,
        "attr_types": attr_types,
        "calls": calls,
    }


def _dotted(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# --- the graph ---------------------------------------------------------------

class ProjectGraph:
    """Queryable view over every file summary in the scan scope."""

    def __init__(self, root: str, summaries: dict):
        self.root = root
        self.files = summaries                      # relpath -> summary
        self._by_module = {s["module"]: rp for rp, s in summaries.items()}
        # import edges restricted to project-local modules
        self.imports: dict = {}                     # relpath -> set(relpath)
        for rp, s in summaries.items():
            deps = set()
            for m in s["imports"]:
                dep = self.relpath_of(m)
                if dep and dep != rp:
                    deps.add(dep)
            # `from pkg import sub` records module "pkg" but binds the
            # submodule — follow those bindings so the edge lands on pkg/sub
            for bound in s["bindings"].values():
                if ":" in bound:
                    modpart, attr = bound.split(":", 1)
                    dep = self._by_module.get(f"{modpart}.{attr}")
                    if dep and dep != rp:
                        deps.add(dep)
            self.imports[rp] = deps
        self.reverse_imports: dict = {rp: set() for rp in summaries}
        for rp, deps in self.imports.items():
            for dep in deps:
                self.reverse_imports.setdefault(dep, set()).add(rp)

    def relpath_of(self, module: str):
        """relpath for a dotted module, tolerating ``from pkg import name``
        edges that point at an attribute of a module."""
        while module:
            rp = self._by_module.get(module)
            if rp:
                return rp
            if "." not in module:
                return None
            module = module.rsplit(".", 1)[0]
        return None

    def reverse_closure(self, relpaths) -> set:
        """``relpaths`` plus everything that transitively imports them."""
        seen = set()
        stack = [rp for rp in relpaths]
        while stack:
            rp = stack.pop()
            if rp in seen:
                continue
            seen.add(rp)
            stack.extend(self.reverse_imports.get(rp, ()))
        return seen

    # --- symbol / call resolution ---------------------------------------
    def binding_target(self, relpath: str, name: str):
        """Resolve a local name to ("module", None) or ("module", "attr")."""
        s = self.files.get(relpath)
        if not s:
            return None
        bound = s["bindings"].get(name)
        if bound is None:
            return None
        if ":" in bound:
            modpart, attr = bound.split(":", 1)
            # `from pkg import sub` where pkg.sub is itself a module
            if f"{modpart}.{attr}" in self._by_module:
                return (f"{modpart}.{attr}", None)
            return (modpart, attr)
        return (bound, None)

    def constant(self, relpath: str, dotted: str):
        """Value of a possibly-qualified constant reference as seen from
        ``relpath``: ``NAME``, ``Class.NAME``, ``alias.NAME``,
        ``alias.Class.NAME`` — following one import hop."""
        s = self.files.get(relpath)
        if not s:
            return None
        hit = s["constants"].get(dotted)
        if hit is not None:
            return hit[0]
        head, _, rest = dotted.partition(".")
        if not rest:
            # bare name bound by `from mod import NAME`
            target = self.binding_target(relpath, dotted)
            if target is None or target[1] is None:
                return None
            dep = self.relpath_of(target[0])
            if dep is None:
                return None
            hit = self.files[dep]["constants"].get(target[1])
            return hit[0] if hit is not None else None
        target = self.binding_target(relpath, head)
        if target is None:
            return None
        module, attr = target
        dep = self.relpath_of(module)
        if dep is None:
            return None
        remote = f"{attr}.{rest}" if attr else rest
        hit = self.files[dep]["constants"].get(remote)
        if hit is None and attr is None:
            hit = self.files[dep]["constants"].get(rest)
        return hit[0] if hit is not None else None

    def resolve_call(self, relpath: str, scope: str, dotted: str):
        """Map a dotted callee as written in ``relpath`` to a project
        function: returns (relpath, qualname) or None.

        Handles: bare local names, ``self.method`` (within ``scope``'s
        class), ``mod.func`` / ``alias.func`` via imports, and
        ``from mod import func`` bindings.
        """
        s = self.files.get(relpath)
        if not s:
            return None
        head, _, rest = dotted.partition(".")
        if head == "self" and rest:
            cls = scope.split(".")[0] if "." in scope else None
            if cls is None:
                return None
            if "." not in rest:
                if rest in (s["classes"].get(cls) or ()):
                    return (relpath, f"{cls}.{rest}")
                return None
            # self.attr.method() — follow the instance-attribute type
            attr, _, meth = rest.partition(".")
            if "." in meth:
                return None
            ctor = (s.get("attr_types", {}).get(cls) or {}).get(attr)
            if not ctor:
                return None
            target = self.resolve_class(relpath, ctor)
            if target is None:
                return None
            dep, cls_name = target
            if meth in (self.files[dep]["classes"].get(cls_name) or ()):
                return (dep, f"{cls_name}.{meth}")
            return None
        if not rest:
            if dotted in s["functions"]:
                return (relpath, dotted)
            target = self.binding_target(relpath, dotted)
            if target:
                module, attr = target
                dep = self.relpath_of(module)
                if dep and attr and attr in self.files[dep]["functions"]:
                    return (dep, attr)
            return None
        target = self.binding_target(relpath, head)
        if target is None:
            return None
        module, attr = target
        dep = self.relpath_of(module)
        if dep is None:
            return None
        name = f"{attr}.{rest}" if attr else rest
        if name in self.files[dep]["functions"]:
            return (dep, name)
        return None

    def resolve_class(self, relpath: str, dotted: str):
        """(relpath, class_name) for a class reference as seen from
        ``relpath`` — local class or one import hop."""
        s = self.files.get(relpath)
        if not s:
            return None
        if "." not in dotted:
            if dotted in s["classes"]:
                return (relpath, dotted)
            target = self.binding_target(relpath, dotted)
            if target:
                module, attr = target
                dep = self.relpath_of(module)
                if dep and attr and attr in self.files[dep]["classes"]:
                    return (dep, attr)
            return None
        head, _, rest = dotted.partition(".")
        target = self.binding_target(relpath, head)
        if target is None or "." in rest:
            return None
        module, attr = target
        dep = self.relpath_of(module)
        if dep is None or attr is not None:
            return None
        if rest in self.files[dep]["classes"]:
            return (dep, rest)
        return None

    def resolve_symbol(self, relpath: str, dotted: str):
        """(relpath, name) for any module-level symbol reference — unlike
        :meth:`resolve_call` the target need not be a def (jitted callables
        are often assignments: ``step = jax.jit(fn, donate_argnums=0)``)."""
        s = self.files.get(relpath)
        if not s:
            return None
        head, _, rest = dotted.partition(".")
        if not rest:
            target = self.binding_target(relpath, dotted)
            if target and target[1]:
                dep = self.relpath_of(target[0])
                if dep:
                    return (dep, target[1])
            return (relpath, dotted)
        if "." in rest:
            return None
        target = self.binding_target(relpath, head)
        if target is None:
            return None
        module, attr = target
        dep = self.relpath_of(module)
        if dep is None:
            return None
        return (dep, f"{attr}.{rest}" if attr else rest)


# --- the incremental engine --------------------------------------------------

def _sha1(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()


def _engine_sig(rules) -> str:
    basis = ",".join(sorted(r.id for r in rules)) + f"|v{SUMMARY_VERSION}"
    return _sha1(basis.encode())


def _finding_from_json(root: str, doc: dict) -> Finding:
    relpath = doc["path"]
    return Finding(
        rule=doc["rule"], severity=doc["severity"],
        path=os.path.join(root, *relpath.split("/")), relpath=relpath,
        line=doc["line"], col=doc.get("col", 0), message=doc["message"],
        line_text=doc.get("line_text", ""))


def run_project(root: str, paths, rules, exclude=(), baseline_entries=(),
                cache_path=None, changed_scope=None) -> RunResult:
    """Project-graph engine: incremental per-file analysis + whole-program
    rules over the merged fact set.

    ``cache_path``: absolute path of the incremental cache (None disables
    caching — every file is parsed, which is exactly what the legacy shims
    want for their synthetic trees).
    ``changed_scope``: optional set of relpaths; when given, reported
    findings are filtered to those files (``--changed`` mode). Analysis
    scope is unaffected — cache hits make the full pass cheap.
    """
    t0 = time.perf_counter()
    root = os.path.abspath(root)
    runctx = RunContext(root=root)
    result = RunResult()

    file_rules = [r for r in rules if not getattr(r, "project", False)]
    project_rules = [r for r in rules if getattr(r, "project", False)]

    dispatch: dict = {}
    for rule in file_rules:
        for nt in rule.node_types:
            dispatch.setdefault(nt, []).append(rule)

    # --- discovery + hashing ---
    abs_paths = list(iter_py_files(root, paths, exclude))
    by_rel: dict = {}
    hashes: dict = {}
    sources: dict = {}
    for path in abs_paths:
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        by_rel[relpath] = path
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            continue
        hashes[relpath] = _sha1(data)
        sources[relpath] = data
    result.files_scanned = len(hashes)

    # --- cache + dirty set ---
    sig = _engine_sig(rules)
    cached = cache_mod.load(cache_path, sig) if cache_path else {}
    cached = {rp: e for rp, e in cached.items() if rp in hashes}
    changed = {rp for rp in hashes
               if rp not in cached or cached[rp].get("hash") != hashes[rp]}
    if changed and cached:
        old_graph = ProjectGraph(
            root, {rp: e["summary"] for rp, e in cached.items()})
        dirty = old_graph.reverse_closure(changed) | changed
    else:
        dirty = set(changed)
    dirty &= set(hashes)

    entries: dict = {}          # relpath -> cache entry (fresh or reused)
    raw: list = []              # Finding (pre-suppression)
    suppressions: dict = {}     # relpath -> _Suppressions

    for relpath in sorted(hashes):
        path = by_rel[relpath]
        if relpath not in dirty and relpath in cached:
            entry = cached[relpath]
            entries[relpath] = entry
            suppressions[relpath] = _Suppressions.from_json(
                entry["suppressions"])
            raw.extend(_finding_from_json(root, d) for d in entry["findings"])
            result.cache_hits += 1
            continue

        result.analyzed.append(relpath)
        try:
            source = sources[relpath].decode("utf-8")
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError) as e:
            lineno = getattr(e, "lineno", 0) or 0
            msg = getattr(e, "msg", None) or str(e)
            raw.append(Finding(
                rule=SYNTAX_ERROR, severity="error", path=path,
                relpath=relpath, line=lineno,
                col=getattr(e, "offset", 0) or 0,
                message=f"unparseable: {msg}"))
            runctx.failed.append((path, e))
            continue  # never cached: re-analyzed until it parses

        ctx = FileContext(root, path, source, tree)
        runctx.files.append(ctx)
        suppressions[relpath] = ctx.suppressions

        file_findings: list = []
        active = [r for r in file_rules if r.applies_to(relpath)]
        for rule in active:
            begin = getattr(rule, "begin_file", None)
            if begin is not None:
                begin(ctx)
        file_dispatch = {
            nt: [r for r in rs if r in active] for nt, rs in dispatch.items()}
        sink: list = []
        _walk_and_dispatch(ctx, file_dispatch, sink)
        file_findings.extend(f for _r, f in sink)
        for rule in active:
            file_findings.extend(rule.check_file(ctx) or ())

        facts: dict = {}
        for rule in project_rules:
            if not rule.applies_to(relpath):
                continue
            fact = rule.collect(ctx)
            if fact:
                facts[rule.id] = fact

        entries[relpath] = {
            "hash": hashes[relpath],
            "summary": collect_summary(ctx),
            "findings": [f.to_json() for f in file_findings],
            "suppressions": ctx.suppressions.to_json(),
            "facts": facts,
        }
        raw.extend(file_findings)

    # --- whole-program pass (always runs, over fresh + cached facts) ---
    graph = ProjectGraph(
        root, {rp: e["summary"] for rp, e in entries.items()})
    result.graph = graph
    for rule in project_rules:
        facts = {rp: e["facts"][rule.id] for rp, e in entries.items()
                 if rule.id in e.get("facts", {})}
        for f in rule.finalize_project(graph, facts) or ():
            raw.append(f)
    for rule in file_rules:
        for f in rule.finalize(runctx) or ():
            raw.append(f)

    # bare suppression pragmas are findings every run, cached or not
    for relpath, sup in suppressions.items():
        for lineno in sup.bare_lines:
            raw.append(Finding(
                rule=BARE_SUPPRESSION, severity="error",
                path=by_rel[relpath], relpath=relpath, line=lineno, col=0,
                message="suppression pragma without a reason — write "
                        "`# fedlint: disable=<rule> <why it is safe>`"))

    # --- suppression + baseline + scope filters ---
    baseline_keys: dict = {}
    for e in baseline_entries or ():
        baseline_keys.setdefault(
            (e.get("rule"), e.get("path"), e.get("fingerprint")), []).append(e)
    matched_baseline = set()

    for finding in raw:
        sup = suppressions.get(finding.relpath)
        if (sup is not None and finding.rule != BARE_SUPPRESSION
                and sup.matches(finding.rule, finding.line)):
            result.suppressed.append(finding)
            continue
        key = (finding.rule, finding.relpath, finding.fingerprint)
        if key in baseline_keys:
            matched_baseline.add(key)
            result.baselined.append(finding)
            continue
        if changed_scope is not None and finding.relpath not in changed_scope:
            continue
        result.findings.append(finding)

    for key, bl in baseline_keys.items():
        if key not in matched_baseline:
            result.stale_baseline.extend(bl)

    if cache_path:
        cache_mod.save(cache_path, sig, entries)

    result.findings.sort(key=lambda f: (f.relpath, f.line, f.rule))
    result.wall_time_s = time.perf_counter() - t0
    return result


def changed_files(root: str) -> set:
    """Repo-relative paths of files changed vs HEAD (staged, unstaged, and
    untracked) — the ``--changed`` scope seed."""
    import subprocess

    out = set()
    for args in (["git", "-C", root, "diff", "--name-only", "HEAD"],
                 ["git", "-C", root, "ls-files", "--others",
                  "--exclude-standard"]):
        try:
            proc = subprocess.run(args, capture_output=True, text=True,
                                  timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return set()
        if proc.returncode != 0:
            return set()
        out |= {ln.strip() for ln in proc.stdout.splitlines() if ln.strip()}
    return {p for p in out if p.endswith(".py")}
