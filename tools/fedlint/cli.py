"""fedlint CLI.

    python -m tools.fedlint                  # full configured run, text output
    python -m tools.fedlint --format json    # machine output (CI, bench_watch)
    python -m tools.fedlint --rules host-sync,retrace-risk fedml_tpu/serving
    python -m tools.fedlint --list-rules
    python -m tools.fedlint --write-baseline --reason "pre-ISSUE-9 burn-down"
    python -m tools.fedlint --sarif out.sarif   # SARIF 2.1.0 for code scanning
    python -m tools.fedlint --changed           # git-diff scope + import closure

Exit codes: 0 clean (no unsuppressed error-severity findings), 1 findings,
2 usage/config/baseline error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import api, baseline as baseline_mod
from .config import load_config
from .registry import all_rules, get_rules


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fedlint",
        description="Unified JAX-aware static analysis for the fedml_tpu tree.")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: [tool.fedlint] paths)")
    p.add_argument("--root", default=None,
                   help="repo root (default: autodetected from this file)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all minus "
                        "config-disabled)")
    p.add_argument("--disable", default=None,
                   help="comma-separated rule ids to skip for this run")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file (show grandfathered findings)")
    p.add_argument("--write-baseline", action="store_true",
                   help="park all current unsuppressed findings in the "
                        "baseline file (requires --reason)")
    p.add_argument("--reason", default=None,
                   help="reason string recorded on baseline entries")
    p.add_argument("--statistics", action="store_true",
                   help="append per-rule counts to text output")
    p.add_argument("--sarif", metavar="PATH", default=None,
                   help="also write findings as SARIF 2.1.0 to PATH "
                        "('-' for stdout)")
    p.add_argument("--changed", action="store_true",
                   help="report only findings in files changed per git "
                        "(plus their import-reverse-closure)")
    p.add_argument("--no-cache", action="store_true",
                   help="ignore and do not write the incremental cache")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    root = os.path.abspath(args.root) if args.root else api.repo_root()
    cfg = load_config(root)

    if args.list_rules:
        for rule in all_rules(cfg):
            print(f"{rule.id:24s} [{rule.severity}] {rule.description}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
    disabled = set(cfg.get("disable") or ())
    if args.disable:
        disabled |= {r.strip() for r in args.disable.split(",") if r.strip()}

    try:
        rules = (get_rules(rule_ids, options=cfg) if rule_ids
                 else [r for r in all_rules(cfg) if r.id not in disabled])
    except KeyError as e:
        print(f"fedlint: {e.args[0]}", file=sys.stderr)
        return 2

    baseline_path = os.path.join(root, cfg["baseline"])
    entries = []
    if not args.no_baseline and not args.write_baseline:
        try:
            entries = baseline_mod.load(baseline_path)
        except (baseline_mod.BaselineError, ValueError) as e:
            print(f"fedlint: bad baseline {baseline_path}: {e}", file=sys.stderr)
            return 2

    changed_scope = None
    if args.changed:
        from .project import changed_files
        changed_scope = changed_files(root)
        if not changed_scope:
            print("fedlint: clean — no changed .py files")
            return 0

    from .project import run_project
    cache_path = None if args.no_cache else os.path.join(root, cfg["cache"])
    result = run_project(root, args.paths or cfg["paths"], rules,
                         exclude=cfg["exclude"], baseline_entries=entries,
                         cache_path=cache_path, changed_scope=changed_scope)

    if args.sarif:
        from . import sarif as sarif_mod
        if args.sarif == "-":
            print(json.dumps(sarif_mod.to_sarif(result, rules), indent=2,
                             sort_keys=True))
        else:
            sarif_mod.write(args.sarif, result, rules)

    if args.write_baseline:
        try:
            n = baseline_mod.write(baseline_path, result.findings, args.reason or "")
        except baseline_mod.BaselineError as e:
            print(f"fedlint: {e}", file=sys.stderr)
            return 2
        print(f"fedlint: wrote {n} baseline entr{'y' if n == 1 else 'ies'} "
              f"to {os.path.relpath(baseline_path, root)}")
        return 0

    if args.format == "json":
        print(json.dumps(result.to_json(), indent=2, sort_keys=True))
        return result.exit_code()

    for f in result.findings:
        print(f.render())
        if f.line_text.strip():
            print(f"    {f.line_text.strip()}")
    if result.stale_baseline:
        for e in result.stale_baseline:
            print(f"stale baseline entry: {e['path']} [{e['rule']}] — fixed? "
                  "remove it from the baseline")
    cache_note = (f"cache {result.cache_hit_rate:.0%} "
                  f"({result.files_analyzed} analyzed) · "
                  f"{result.wall_time_s:.2f}s")
    if args.statistics or result.findings:
        by_rule: dict = {}
        for f in result.findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        stats = ", ".join(f"{k}={v}" for k, v in sorted(by_rule.items())) or "none"
        print(
            f"\nfedlint: {len(result.findings)} finding(s) "
            f"[{stats}] · {len(result.suppressed)} suppressed · "
            f"{len(result.baselined)} baselined · "
            f"{result.files_scanned} files · {cache_note}")
    elif not result.findings:
        print(
            f"fedlint: clean — {result.files_scanned} files, "
            f"{len(result.suppressed)} suppressed, "
            f"{len(result.baselined)} baselined, {cache_note}")
    return result.exit_code()


if __name__ == "__main__":
    raise SystemExit(main())
