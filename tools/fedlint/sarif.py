"""SARIF 2.1.0 output for fedlint (``fedlint --sarif out.sarif``).

The writer emits the minimal-but-complete shape GitHub code scanning and
IDE SARIF viewers consume: one run, a tool driver with per-rule metadata,
and one result per live finding (suppressed/baselined findings are emitted
with a ``suppressions`` entry so viewers can show them greyed out, which
is what reviewers expect from a baseline-bearing linter).

``validate()`` is a hand-rolled structural check against the SARIF 2.1.0
schema's required core (this environment has no ``jsonschema``): it
returns a list of problems, empty when the document is well-formed. It is
deliberately strict about the properties fedlint relies on — version,
tool.driver.name, ruleId/message/locations shape — rather than a full
schema walk.
"""

from __future__ import annotations

import json

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

_LEVELS = {"error": "error", "warn": "warning"}


def _result(finding, suppressed_kind=None) -> dict:
    res = {
        "ruleId": finding.rule,
        "level": _LEVELS.get(finding.severity, "warning"),
        "message": {"text": finding.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": finding.relpath},
                "region": {"startLine": max(1, finding.line),
                           "startColumn": max(1, finding.col + 1)},
            },
        }],
        "partialFingerprints": {"fedlint/v1": finding.fingerprint},
    }
    if suppressed_kind:
        res["suppressions"] = [{"kind": "inSource",
                                "justification": suppressed_kind}]
    return res


def to_sarif(result, rules) -> dict:
    """SARIF 2.1.0 document for a :class:`~tools.fedlint.core.RunResult`."""
    rule_meta = [
        {"id": r.id,
         "shortDescription": {"text": r.description or r.id},
         "defaultConfiguration": {
             "level": _LEVELS.get(r.severity, "warning")}}
        for r in sorted(rules, key=lambda r: r.id)
    ]
    results = [_result(f) for f in result.findings]
    results += [_result(f, "suppression pragma") for f in result.suppressed]
    results += [_result(f, "reviewed baseline") for f in result.baselined]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "fedlint",
                "informationUri": "docs/static_analysis.md",
                "rules": rule_meta,
            }},
            "results": results,
        }],
    }


def write(path: str, result, rules) -> None:
    doc = to_sarif(result, rules)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def validate(doc) -> list:
    """Structural problems with a SARIF 2.1.0 document ([] == valid)."""
    problems = []

    def need(cond, msg):
        if not cond:
            problems.append(msg)
        return cond

    if not need(isinstance(doc, dict), "document must be an object"):
        return problems
    need(doc.get("version") == SARIF_VERSION,
         f"version must be {SARIF_VERSION!r}")
    runs = doc.get("runs")
    if not need(isinstance(runs, list) and runs, "runs must be a non-empty array"):
        return problems
    for i, run in enumerate(runs):
        where = f"runs[{i}]"
        if not need(isinstance(run, dict), f"{where} must be an object"):
            continue
        driver = (run.get("tool") or {}).get("driver") \
            if isinstance(run.get("tool"), dict) else None
        if need(isinstance(driver, dict), f"{where}.tool.driver required"):
            need(isinstance(driver.get("name"), str) and driver["name"],
                 f"{where}.tool.driver.name must be a non-empty string")
            for j, rule in enumerate(driver.get("rules") or ()):
                need(isinstance(rule, dict) and isinstance(
                    rule.get("id"), str) and rule["id"],
                    f"{where}.tool.driver.rules[{j}].id must be a string")
        for j, res in enumerate(run.get("results") or ()):
            rwhere = f"{where}.results[{j}]"
            if not need(isinstance(res, dict), f"{rwhere} must be an object"):
                continue
            need(isinstance(res.get("ruleId"), str) and res["ruleId"],
                 f"{rwhere}.ruleId must be a non-empty string")
            need(res.get("level") in ("none", "note", "warning", "error"),
                 f"{rwhere}.level must be a SARIF level")
            msg = res.get("message")
            need(isinstance(msg, dict) and isinstance(msg.get("text"), str),
                 f"{rwhere}.message.text required")
            for k, loc in enumerate(res.get("locations") or ()):
                lwhere = f"{rwhere}.locations[{k}]"
                phys = loc.get("physicalLocation") \
                    if isinstance(loc, dict) else None
                if not need(isinstance(phys, dict),
                            f"{lwhere}.physicalLocation required"):
                    continue
                art = phys.get("artifactLocation")
                need(isinstance(art, dict) and isinstance(
                    art.get("uri"), str) and art["uri"],
                    f"{lwhere}...artifactLocation.uri must be a string")
                region = phys.get("region")
                if region is not None:
                    need(isinstance(region, dict) and isinstance(
                        region.get("startLine"), int)
                        and region["startLine"] >= 1,
                        f"{lwhere}...region.startLine must be an int >= 1")
    return problems
