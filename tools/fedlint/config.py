"""``[tool.fedlint]`` config loading.

Python 3.10 has no ``tomllib`` and the repo pins zero new dependencies, so
this is a deliberately minimal TOML subset reader: table headers, string /
bool / int scalars, and (possibly multi-line) arrays of strings. That covers
the whole ``[tool.fedlint]`` block; anything fancier belongs in code, not
config. When running on 3.11+ the real ``tomllib`` is used instead.
"""

from __future__ import annotations

import os
import re

DEFAULTS = {
    # scan scope: the package, the bench driver, and the tooling itself.
    # tests/ are deliberately excluded — lint fixtures must be able to spell
    # violations (ISSUE 8).
    "paths": ["fedml_tpu", "bench.py", "tools"],
    "exclude": ["tests", "__pycache__", "native", "examples", "devops",
                "fixtures"],
    "baseline": "tools/fedlint/baseline.json",
    # modules whose loops are latency-critical: one host sync per iteration
    # multiplies into a bench collapse (r05: 985 tok/s int8 decode)
    "hot-modules": [
        "fedml_tpu/serving/continuous_batching.py",
        "fedml_tpu/serving/paged_kv.py",
        "fedml_tpu/serving/admission.py",
        "fedml_tpu/serving/replica_controller.py",
        "fedml_tpu/serving/endpoint.py",
        "fedml_tpu/core/aggregation/bucketed.py",
        "fedml_tpu/core/aggregation/sharded.py",
        "fedml_tpu/train/llm/llm_trainer.py",
        "fedml_tpu/parallel/fsdp.py",
    ],
    # method names that run on listener/worker threads even though no
    # Thread(target=...) names them directly (comm handler callbacks)
    "thread-entry-methods": ["handle_receive_message"],
    "disable": [],
    # project-graph incremental cache (ISSUE 10); repo-root-relative
    "cache": ".fedlint_cache.json",
    # metric-registry rule: where fedml_* series must be documented/tested
    "metric-doc": "docs/observability.md",
    "metric-tests-dir": "tests",
    # fnmatch patterns exempt from the doc/test contract: "fedml_tpu" is the
    # package name, not a metric, and matches the fedml_* token regex
    "metric-doc-ignore": ["fedml_tpu*"],
    # raw-delta-escape: transport backends reassemble/echo payloads the
    # origination site already sanctioned — below the privacy boundary
    "delta-transport-modules": ["fedml_tpu/core/distributed/communication/*"],
}

_SECTION_RE = re.compile(r"^\s*\[(?P<name>[^\]]+)\]\s*$")
_KEY_RE = re.compile(r"^\s*(?P<key>[A-Za-z0-9_\-\.\"']+)\s*=\s*(?P<val>.*)$")


def _parse_scalar(text: str):
    text = text.strip()
    if text.startswith(("'", '"')):
        return text[1:-1] if len(text) >= 2 else ""
    if text in ("true", "false"):
        return text == "true"
    try:
        return int(text)
    except ValueError:
        return text


def _strip_comment(line: str) -> str:
    # good enough for this block: '#' never appears inside our strings
    out, in_str, quote = [], False, ""
    for ch in line:
        if in_str:
            out.append(ch)
            if ch == quote:
                in_str = False
        elif ch in ("'", '"'):
            in_str, quote = True, ch
            out.append(ch)
        elif ch == "#":
            break
        else:
            out.append(ch)
    return "".join(out)


def _parse_toml_subset(text: str) -> dict:
    data: dict = {}
    section: dict = data
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = _strip_comment(lines[i]).rstrip()
        i += 1
        if not line.strip():
            continue
        m = _SECTION_RE.match(line)
        if m:
            section = data
            for part in m.group("name").split("."):
                section = section.setdefault(part.strip(), {})
            continue
        m = _KEY_RE.match(line)
        if not m:
            continue
        key = m.group("key").strip().strip("\"'")
        val = m.group("val").strip()
        if val.startswith("["):
            buf = val
            while "]" not in buf and i < len(lines):
                buf += " " + _strip_comment(lines[i]).strip()
                i += 1
            inner = buf[buf.index("[") + 1: buf.rindex("]")]
            items = [s for s in re.split(r"\s*,\s*", inner.strip()) if s]
            section[key] = [_parse_scalar(s) for s in items]
        else:
            section[key] = _parse_scalar(val)
    return data


def load_config(root: str) -> dict:
    """DEFAULTS overlaid with ``pyproject.toml [tool.fedlint]`` (if any)."""
    cfg = {k: (list(v) if isinstance(v, list) else v)
           for k, v in DEFAULTS.items()}
    pyproject = os.path.join(root, "pyproject.toml")
    if not os.path.exists(pyproject):
        return cfg
    with open(pyproject, encoding="utf-8") as f:
        text = f.read()
    try:
        import tomllib  # Python 3.11+
        data = tomllib.loads(text)
    except ModuleNotFoundError:
        data = _parse_toml_subset(text)
    block = data.get("tool", {}).get("fedlint", {})
    for key, val in block.items():
        if isinstance(val, dict):
            cfg.setdefault(key, {})
            cfg[key] = {**cfg.get(key, {}), **val}
        else:
            cfg[key] = val
    return cfg
