"""Rule registry: the one place that knows every rule."""

from __future__ import annotations

from .rules.cardinality import LabelCardinalityRule
from .rules.donation import DonationMisuseRule
from .rules.host_sync import HostSyncRule
from .rules.interproc import (InterprocDonationRule, InterprocHostSyncRule,
                              InterprocRetraceRule)
from .rules.lock_graph import LockGraphRule
from .rules.locking import LockDisciplineRule
from .rules.metrics import MetricRegistryRule
from .rules.privacy import RawDeltaEscapeRule
from .rules.protocol import ProtocolContractRule
from .rules.resilience import BareSleepRule, OrbaxContainmentRule
from .rules.retrace import RetraceRiskRule
from .rules.serving import AdmissionRejectRule, HotSpanRule
from .rules.sharding import DeviceGetRule, ShardingContainmentRule
from .rules.telemetry import ExcepthookRule, RecorderKindRule, ReservedKeyRule
from .rules.timing import WallClockRule

_RULE_CLASSES = (
    # ported from the five check_*.py walkers (PRs 2–7)
    WallClockRule,
    ReservedKeyRule,
    RecorderKindRule,
    ExcepthookRule,
    BareSleepRule,
    OrbaxContainmentRule,
    HotSpanRule,
    AdmissionRejectRule,
    ShardingContainmentRule,
    DeviceGetRule,
    # the JAX-aware rules none of the ad-hoc walkers could express (ISSUE 8)
    RetraceRiskRule,
    HostSyncRule,
    DonationMisuseRule,
    LockDisciplineRule,
    # whole-program rules over the cached project graph (ISSUE 10)
    ProtocolContractRule,
    LockGraphRule,
    InterprocDonationRule,
    InterprocHostSyncRule,
    InterprocRetraceRule,
    MetricRegistryRule,
    # per-rank/tenant label-cardinality budget enforcement (ISSUE 19)
    LabelCardinalityRule,
    # privacy boundary: no raw client delta on the uplink (ISSUE 20)
    RawDeltaEscapeRule,
)


def all_rules(options: dict = None) -> list:
    rules = [cls() for cls in _RULE_CLASSES]
    if options:
        for rule in rules:
            rule.configure(options)
    return rules


def get_rules(ids, options: dict = None) -> list:
    wanted = set(ids)
    rules = [r for r in all_rules(options) if r.id in wanted]
    missing = wanted - {r.id for r in rules}
    if missing:
        raise KeyError(f"unknown fedlint rule id(s): {sorted(missing)}")
    return rules
