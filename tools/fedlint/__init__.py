"""fedlint — the repo's unified JAX-aware static-analysis framework.

One shared AST walk, many rules. PRs 2–7 each grew a bespoke line-scanning
lint (``tools/check_*.py``); fedlint replaces the five walkers with a single
engine (``core.py``), a ``Rule`` plugin API (``rules/``), one suppression
syntax (``# fedlint: disable=RULE[,RULE] <reason>``), a checked-in baseline
for grandfathered findings, and config in ``pyproject.toml [tool.fedlint]``.

Entry points:

* ``python -m tools.fedlint`` (CLI, text/JSON output, used by CI and
  ``tools/bench_watch.sh``),
* ``fedlint`` console script (``pyproject.toml [project.scripts]``),
* :func:`tools.fedlint.api.run_rules` (programmatic — the legacy
  ``tools/check_*.py`` shims ride it to preserve their exit-code contracts).

See ``docs/static_analysis.md`` for the rule catalogue and the
suppression/baseline workflow.
"""

from .core import Finding, Rule, RunResult, run  # noqa: F401
from .registry import all_rules, get_rules  # noqa: F401

__all__ = ["Finding", "Rule", "RunResult", "run", "all_rules", "get_rules"]
