"""``donation-misuse``: a donated buffer read after the donating call.

``donate_argnums`` hands the argument's device buffer to XLA for reuse —
after the call the Python reference points at invalidated memory, and JAX
raises (or silently copies, depending on backend) on the next read. The
aggregation engine and the fused server step lean hard on donation
(PR 1/PR 7); this rule keeps the discipline honest:

* it collects every donating callable in the module — ``name = jax.jit(fn,
  donate_argnums=...)``, ``self._step = jax.jit(..., donate_argnums=...)``
  and ``@partial(jax.jit, donate_argnums=...)`` decorations (plus
  ``donate_argnames`` resolved against the wrapped def when visible);
* at each call site, a plain-name argument in a donated position whose
  name is read again later in the same function — with no rebinding in
  between — is a finding. The canonical safe shape ``state = step(state)``
  rebinds at the call statement itself and is never flagged.

Known-safe re-reads (e.g. an error path that only logs shapes) get
``# fedlint: disable=donation-misuse <why the buffer is not dereferenced>``.
"""

from __future__ import annotations

import ast

from ..core import Rule
from ._util import const_int_tuple, const_str_tuple, dotted, is_jit_callable, param_names


def _donation_keywords(call: ast.Call):
    """(argnums tuple or None, argnames tuple or None) from a jit call."""
    nums = names = None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            nums = const_int_tuple(kw.value)
        elif kw.arg == "donate_argnames":
            names = const_str_tuple(kw.value)
    return nums, names


def _jit_call_with_donation(call: ast.Call):
    """For ``jax.jit(fn?, donate_...)`` or ``partial(jax.jit, donate_...)``
    return (wrapped_name_or_None, argnums, argnames); else None."""
    if is_jit_callable(call.func):
        nums, names = _donation_keywords(call)
        if nums is None and names is None:
            return None
        wrapped = None
        if call.args and isinstance(call.args[0], ast.Name):
            wrapped = call.args[0].id
        return wrapped, nums, names
    func = call.func
    is_partial = (isinstance(func, ast.Name) and func.id == "partial") or (
        isinstance(func, ast.Attribute) and func.attr == "partial")
    if is_partial and call.args and is_jit_callable(call.args[0]):
        nums, names = _donation_keywords(call)
        if nums is None and names is None:
            return None
        return None, nums, names
    return None


def _names_to_nums(names, fn_def):
    if not names or fn_def is None:
        return ()
    order = [p.arg for p in fn_def.args.posonlyargs + fn_def.args.args]
    return tuple(order.index(n) for n in names if n in order)


class DonationMisuseRule(Rule):
    id = "donation-misuse"
    severity = "error"
    description = "variable read again after being donated to a jitted call"

    def check_file(self, ctx):
        defs_by_name: dict = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, node)

        donors: dict = {}  # dotted callee name -> tuple of donated positions
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                parsed = _jit_call_with_donation(node.value)
                if parsed:
                    wrapped, nums, names = parsed
                    positions = tuple(nums or ()) + _names_to_nums(
                        names, defs_by_name.get(wrapped))
                    if positions:
                        for tgt in node.targets:
                            key = dotted(tgt)
                            if key:
                                donors[key] = positions
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        parsed = _jit_call_with_donation(dec)
                        if parsed:
                            _w, nums, names = parsed
                            positions = tuple(nums or ()) + _names_to_nums(
                                names, node)
                            if positions:
                                donors[node.name] = positions
        if not donors:
            return

        for scope in self._scopes(ctx.tree):
            yield from self._check_scope(scope, donors, ctx)

    def _scopes(self, tree):
        yield tree
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    def _check_scope(self, scope, donors, ctx):
        # own nodes only: stop at nested function boundaries
        own: list = []
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            own.append(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

        calls = [n for n in own if isinstance(n, ast.Call)
                 and dotted(n.func) in donors]
        if not calls:
            return
        names_in_scope = [n for n in own if isinstance(n, ast.Name)]
        for call in calls:
            positions = donors[dotted(call.func)]
            call_end = getattr(call, "end_lineno", call.lineno)
            stmt = self._statement_of(call, ctx, scope)
            stmt_binds = self._bound_names(stmt)
            for pos in positions:
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                if not isinstance(arg, ast.Name):
                    continue
                if arg.id in stmt_binds:
                    continue  # state = step(state): rebinding at the call
                later_reads = sorted(
                    (n for n in names_in_scope
                     if n.id == arg.id and isinstance(n.ctx, ast.Load)
                     and n.lineno > call_end),
                    key=lambda n: (n.lineno, n.col_offset))
                rebinds = sorted(
                    n.lineno for n in names_in_scope
                    if n.id == arg.id and isinstance(n.ctx, ast.Store)
                    and n.lineno > call_end)
                for read in later_reads:
                    if any(rl <= read.lineno for rl in rebinds):
                        break  # rebound before (or on the line of) this read
                    yield self.make(
                        ctx, read,
                        f"`{arg.id}` read after being donated (position "
                        f"{pos}) to `{dotted(call.func)}` at line "
                        f"{call.lineno} — the buffer is invalidated by "
                        "donate_argnums; use the call's return value or "
                        "drop the donation")
                    break

    def _statement_of(self, node, ctx, scope):
        cur = node
        while cur is not None and cur is not scope:
            parent = ctx.parent(cur)
            if isinstance(cur, ast.stmt):
                return cur
            cur = parent
        return node

    def _bound_names(self, stmt) -> set:
        bound = set()
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        bound.add(n.id)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            for n in ast.walk(stmt.target):
                if isinstance(n, ast.Name):
                    bound.add(n.id)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            for n in ast.walk(stmt.target):
                if isinstance(n, ast.Name):
                    bound.add(n.id)
        return bound
