"""fedlint rule modules. Each module defines one rule family; the registry
(:mod:`tools.fedlint.registry`) instantiates them all."""
