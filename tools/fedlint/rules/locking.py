"""``lock-discipline``: shared mutable state written off-thread without the
lock that guards it everywhere else.

The codebase is full of worker threads — comm handler callbacks, the
continuous-batching worker, the async checkpoint waiter, statusz/metrics
servers — and every one of them shares state with the main thread. The
convention is consistent: state mutated from a worker is guarded by a
``threading.Lock``/``RLock``/``Condition`` held in a ``with`` block. This
rule mechanizes the convention:

* **protected map** — for each class, every ``self.<attr>`` written (or
  mutated via ``.append/.pop/...``) inside ``with self.<lock>:`` anywhere
  in the class is recorded as guarded by that lock. Module-level globals
  written under a module-level lock are tracked the same way.
* **entry points** — methods handed to ``threading.Thread(target=...)``,
  callbacks registered via ``register_message_receive_handler``, and the
  method names in ``[tool.fedlint] thread-entry-methods`` (default:
  ``handle_receive_message``) run off-thread.
* a write to a *protected* attribute from an *entry point* that is not
  itself under a ``with`` on one of that attribute's locks is a finding.

Benign unlocked writes (thread-confined state, pre-start initialization)
get ``# fedlint: disable=lock-discipline <why no lock is needed>``.
"""

from __future__ import annotations

import ast

from ..core import Rule
from ._util import dotted

_LOCK_FACTORIES = ("Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore")
_MUTATORS = ("append", "extend", "add", "insert", "remove", "discard", "pop",
             "popleft", "appendleft", "clear", "update", "setdefault")


def _is_lock_ctor(call: ast.AST) -> bool:
    if not isinstance(call, ast.Call):
        return False
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _LOCK_FACTORIES:
        return isinstance(f.value, ast.Name) and f.value.id == "threading"
    if isinstance(f, ast.Name) and f.id in _LOCK_FACTORIES:
        return True
    return False


def _self_attr(node: ast.AST):
    """'attr' for a ``self.attr`` chain head, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _written_self_attrs(node: ast.AST):
    """(attr, anchor_node) pairs for self-state mutations inside ``node``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                attr = _self_attr(tgt)
                if attr:
                    yield attr, sub
                # self.x[k] = v
                if isinstance(tgt, ast.Subscript):
                    attr = _self_attr(tgt.value)
                    if attr:
                        yield attr, sub
        elif isinstance(sub, ast.AugAssign):
            attr = _self_attr(sub.target)
            if attr:
                yield attr, sub
            if isinstance(sub.target, ast.Subscript):
                attr = _self_attr(sub.target.value)
                if attr:
                    yield attr, sub
        elif isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                attr = _self_attr(f.value)
                if attr:
                    yield attr, sub


def _written_globals(fn: ast.AST):
    declared = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Global):
            declared.update(sub.names)
    if not declared:
        return
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                if isinstance(tgt, ast.Name) and tgt.id in declared:
                    yield tgt.id, sub
        elif isinstance(sub, ast.AugAssign):
            if isinstance(sub.target, ast.Name) and sub.target.id in declared:
                yield sub.target.id, sub


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    severity = "error"
    description = ("shared state written from a thread-worker entry point "
                   "without the lock guarding it elsewhere")

    def __init__(self):
        self.entry_methods: tuple = ("handle_receive_message",)

    def configure(self, options):
        methods = options.get("thread-entry-methods")
        if methods:
            self.entry_methods = tuple(methods)

    # ------------------------------------------------------------------
    def check_file(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(node, ctx)
        yield from self._check_module_level(ctx)

    # ------------------------------------------------------------------
    def _check_class(self, cls, ctx):
        lock_attrs = set()
        aliases = {}  # Condition attr -> the Lock it wraps
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr:
                        lock_attrs.add(attr)
                        # self._work = threading.Condition(self._lock):
                        # holding the condition IS holding the lock
                        call = node.value
                        if call.args:
                            inner = _self_attr(call.args[0])
                            if inner:
                                aliases[attr] = inner
        if not lock_attrs:
            return

        def canon(attr):
            seen = set()
            while attr in aliases and attr not in seen:
                seen.add(attr)
                attr = aliases[attr]
            return attr

        # attr -> set of lock attrs seen guarding it anywhere in the class
        protected: dict = {}
        for node in ast.walk(cls):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            held = {
                canon(_self_attr(item.context_expr))
                for item in node.items
                if _self_attr(item.context_expr) in lock_attrs
            } - {None}
            if not held:
                continue
            for attr, _anchor in _written_self_attrs(node):
                protected.setdefault(attr, set()).update(held)
        if not protected:
            return

        entries = self._entry_methods(cls)
        for meth in entries:
            for attr, anchor in _written_self_attrs(meth):
                locks = protected.get(attr)
                if not locks:
                    continue
                if self._held_at(anchor, locks, meth, ctx, canon):
                    continue
                lock_names = " / ".join(f"self.{l}" for l in sorted(locks))
                yield self.make(
                    ctx, anchor,
                    f"`self.{attr}` written on thread-entry path "
                    f"{cls.name}.{meth.name}() without holding "
                    f"{lock_names} — the lock that guards it everywhere "
                    "else; wrap the write in `with ...:` or record why the "
                    "state is thread-confined")

    def _entry_methods(self, cls):
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        entries = {}
        for name in self.entry_methods:
            if name in methods:
                entries[name] = methods[name]
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_thread = ((isinstance(f, ast.Name) and f.id == "Thread")
                         or (isinstance(f, ast.Attribute) and f.attr == "Thread"))
            if is_thread:
                for kw in node.keywords:
                    if kw.arg == "target":
                        attr = _self_attr(kw.value)
                        if attr and attr in methods:
                            entries[attr] = methods[attr]
            is_register = (isinstance(f, ast.Attribute)
                           and f.attr == "register_message_receive_handler")
            if is_register:
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    attr = _self_attr(arg)
                    if attr and attr in methods:
                        entries[attr] = methods[attr]
        return list(entries.values())

    def _held_at(self, node, locks, boundary, ctx, canon) -> bool:
        cur = ctx.parent(node)
        while cur is not None and cur is not boundary:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                held = {canon(_self_attr(i.context_expr))
                        for i in cur.items if _self_attr(i.context_expr)}
                if held & locks:
                    return True
            cur = ctx.parent(cur)
        return False

    # ------------------------------------------------------------------
    def _check_module_level(self, ctx):
        lock_names = set()
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        lock_names.add(tgt.id)
        if not lock_names:
            return

        protected: dict = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            held = set()
            for item in node.items:
                if (isinstance(item.context_expr, ast.Name)
                        and item.context_expr.id in lock_names):
                    held.add(item.context_expr.id)
            if not held:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            protected.setdefault(tgt.id, set()).update(held)
                elif isinstance(sub, ast.AugAssign):
                    if isinstance(sub.target, ast.Name):
                        protected.setdefault(sub.target.id, set()).update(held)
        if not protected:
            return

        module_defs = {n.name: n for n in ctx.tree.body
                       if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        targets = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_thread = ((isinstance(f, ast.Name) and f.id == "Thread")
                         or (isinstance(f, ast.Attribute) and f.attr == "Thread"))
            if not is_thread:
                continue
            for kw in node.keywords:
                if (kw.arg == "target" and isinstance(kw.value, ast.Name)
                        and kw.value.id in module_defs):
                    targets.add(kw.value.id)
        for name in sorted(targets):
            fn = module_defs[name]
            for gname, anchor in _written_globals(fn):
                locks = protected.get(gname)
                if not locks:
                    continue
                if self._global_held_at(anchor, locks, fn, ctx):
                    continue
                yield self.make(
                    ctx, anchor,
                    f"global `{gname}` written in thread target `{name}()` "
                    f"without holding {'/'.join(sorted(locks))} — the lock "
                    "that guards it elsewhere in this module")

    def _global_held_at(self, node, locks, boundary, ctx) -> bool:
        cur = ctx.parent(node)
        while cur is not None and cur is not boundary:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    if (isinstance(item.context_expr, ast.Name)
                            and item.context_expr.id in locks):
                        return True
            cur = ctx.parent(cur)
        return False
