"""``protocol-contract``: the cross-silo wire protocol checked as a whole
program (ISSUE 10).

A **protocol family** is a class that defines class-level ``MSG_TYPE_*``
constants (the three ``MyMessage`` vocabularies: plain, secagg,
lightsecagg — each checked independently; families are keyed by their
defining module, so same-named classes never bleed into each other).
Uses are attributed to a family by resolving the alias a file imported —
``MyMessage.MSG_TYPE_X`` means whatever ``MyMessage`` is bound to *in
that file*.

Per family, across every file in the scan:

* a ``MSG_TYPE_*`` **sent** (``Message(Fam.MSG_TYPE_X, ...)``) must have a
  registered receiver (``register_message_receive_handler``) somewhere,
  and vice versa — ``CONNECTION_IS_READY`` is exempt because the comm
  manager synthesizes that send from the raw value at runtime;
* a ``MSG_ARG_KEY_*`` **written** (``msg.add_params(Fam.KEY, v)``) must be
  **read** (``msg_params.get(Fam.KEY)`` / ``msg[Fam.KEY]``) by some
  receiver;
* a constant **defined but never referenced** anywhere is dead vocabulary;
* families that define ``MSG_ARG_KEY_MODEL_VERSION`` must stamp it on the
  init/sync sends (type name containing ``INIT_CONFIG`` or
  ``SYNC_MODEL``) in the same function — the async staleness policy is
  blind without the version tag.

Deliberate asymmetries (reference-server interop handlers, telemetry-only
keys) get inline ``# fedlint: disable=protocol-contract <reason>`` on the
reported line.
"""

from __future__ import annotations

import ast

from ..core import ProjectRule
from ._util import dotted

_TYPE_MARK = "MSG_TYPE_"
_KEY_MARK = "MSG_ARG_KEY_"
_READ_ATTRS = ("get", "get_params", "pop")
_EXEMPT_TYPES = ("CONNECTION_IS_READY",)
_STAMPED_SENDS = ("INIT_CONFIG", "SYNC_MODEL")
_VERSION_KEY = "MSG_ARG_KEY_MODEL_VERSION"


def _const_ref(node):
    """Dotted text of a ``Alias.MSG_TYPE_X`` / ``Alias.MSG_ARG_KEY_Y``
    reference, or None."""
    d = dotted(node)
    if d and (_TYPE_MARK in d or _KEY_MARK in d) and "." in d:
        return d
    return None


class ProtocolContractRule(ProjectRule):
    id = "protocol-contract"
    severity = "error"
    description = ("cross-silo protocol drift: unhandled/unsent MSG_TYPE, "
                   "written-never-read or dead MSG_ARG_KEY, or an init/sync "
                   "send missing its model-version stamp")

    # ------------------------------------------------------------------
    def collect(self, ctx):
        classes = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            types, keys = {}, {}
            for item in node.body:
                if not isinstance(item, ast.Assign):
                    continue
                if not (isinstance(item.value, ast.Constant)
                        and isinstance(item.value.value, (str, int))):
                    continue
                for tgt in item.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    rec = [item.value.value, item.lineno,
                           ctx.raw_line(item.lineno)]
                    if tgt.id.startswith(_TYPE_MARK):
                        types[tgt.id] = rec
                    elif tgt.id.startswith(_KEY_MARK):
                        keys[tgt.id] = rec
            if types:
                classes[node.name] = {"types": types, "keys": keys}

        sends, registers, writes, reads, others = [], [], [], [], []
        consumed = set()

        def evt(node, fn=None):
            ref = _const_ref(node)
            if ref is None:
                return None
            consumed.add(id(node))
            rec = [ref, node.lineno, ctx.raw_line(node.lineno)]
            if fn is not None:
                rec.append(fn)
            return rec

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = dotted(f)
            fn_node = ctx.enclosing_function(node)
            fn = ctx.qualname(fn_node) if fn_node is not None else ""
            if node.args:
                first = node.args[0]
                if fname.split(".")[-1] == "Message":
                    rec = evt(first, fn)
                    if rec:
                        sends.append(rec)
                        continue
                if fname.endswith("register_message_receive_handler"):
                    rec = evt(first, fn)
                    if rec:
                        registers.append(rec)
                        continue
                if isinstance(f, ast.Attribute) and f.attr == "add_params":
                    rec = evt(first, fn)
                    if rec:
                        writes.append(rec)
                        continue
                if isinstance(f, ast.Attribute) and f.attr in _READ_ATTRS:
                    rec = evt(first, fn)
                    if rec:
                        reads.append(rec)
                        continue
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Subscript):
                ref = _const_ref(node.slice)
                if ref is not None:
                    consumed.add(id(node.slice))
                    reads.append([ref, node.lineno,
                                  ctx.raw_line(node.lineno), ""])
            elif isinstance(node, ast.Attribute) and id(node) not in consumed:
                ref = _const_ref(node)
                # outermost chain only: a.b.C is visited before its .value
                if ref is not None and not any(
                        id(a) in consumed or _const_ref(a)
                        for a in ctx.ancestors(node)
                        if isinstance(a, ast.Attribute)):
                    others.append([ref, node.lineno])

        if not (classes or sends or registers or writes or reads or others):
            return None
        return {"classes": classes, "sends": sends, "registers": registers,
                "writes": writes, "reads": reads, "others": others}

    # ------------------------------------------------------------------
    def _families(self, graph, facts):
        """(module, class) -> {"types", "keys", "relpath"}."""
        fams = {}
        for relpath, f in facts.items():
            mod = graph.files[relpath]["module"] if relpath in graph.files \
                else None
            for cls, body in (f.get("classes") or {}).items():
                fams[(mod, cls)] = {"relpath": relpath, **body}
        return fams

    def _attribute(self, graph, relpath, ref):
        """Resolve ``Alias[.Class].CONSTANT`` to ((module, class), const)."""
        parts = ref.split(".")
        const = parts[-1]
        holder = parts[:-1]
        if not holder:
            return None
        s = graph.files.get(relpath)
        if s is None:
            return None
        if len(holder) == 1 and holder[0] in (s.get("classes") or {}):
            return ((s["module"], holder[0]), const)
        target = graph.binding_target(relpath, holder[0])
        if target is None:
            return None
        module, attr = target
        rest = holder[1:]
        if attr is not None:
            rest = [attr] + rest
        if len(rest) != 1:
            return None
        dep = graph.relpath_of(module)
        dep_mod = graph.files[dep]["module"] if dep else module
        return ((dep_mod, rest[0]), const)

    # ------------------------------------------------------------------
    def finalize_project(self, graph, facts):
        fams = self._families(graph, facts)
        if not fams:
            return
        use = {fam: {"sends": {}, "registers": {}, "writes": {},
                     "reads": {}, "others": set()} for fam in fams}

        for relpath, f in facts.items():
            for bucket in ("sends", "registers", "writes", "reads"):
                for rec in f.get(bucket) or ():
                    ref, line, text = rec[0], rec[1], rec[2]
                    fn = rec[3] if len(rec) > 3 else ""
                    hit = self._attribute(graph, relpath, ref)
                    if hit is None or hit[0] not in fams:
                        continue
                    fam, const = hit
                    use[fam][bucket].setdefault(const, []).append(
                        (relpath, line, text, fn))
            for ref, _line in f.get("others") or ():
                hit = self._attribute(graph, relpath, ref)
                if hit is not None and hit[0] in fams:
                    use[hit[0]]["others"].add(hit[1])

        for fam, body in sorted(fams.items(), key=lambda kv: str(kv[0])):
            u = use[fam]
            yield from self._check_family(graph, fam, body, u)

    def _check_family(self, graph, fam, body, u):
        def_rel = body["relpath"]
        referenced = (set(u["sends"]) | set(u["registers"]) | set(u["writes"])
                      | set(u["reads"]) | u["others"])

        for name, (value, line, text) in sorted(body["types"].items()):
            if any(mark in name for mark in _EXEMPT_TYPES) or value == 0:
                continue
            sent, reg = u["sends"].get(name), u["registers"].get(name)
            if sent and not reg:
                for rel, sline, stext, _fn in sent:
                    yield self.fact_finding(
                        graph.root, rel, sline,
                        f"{fam[1]}.{name} is sent here but no file registers "
                        "a receive handler for it — the message would be "
                        "dropped on the floor", stext)
            elif reg and not sent:
                for rel, rline, rtext, _fn in reg:
                    yield self.fact_finding(
                        graph.root, rel, rline,
                        f"{fam[1]}.{name} has a receive handler here but "
                        "nothing in the tree ever sends it — dead handler "
                        "or a sender lost in a refactor", rtext)
            elif not sent and not reg and name not in referenced:
                yield self.fact_finding(
                    graph.root, def_rel, line,
                    f"{fam[1]}.{name} is defined but never sent, handled, "
                    "or referenced — dead protocol vocabulary", text)

        for name, (value, line, text) in sorted(body["keys"].items()):
            written, read = u["writes"].get(name), u["reads"].get(name)
            if written and not read:
                for rel, wline, wtext, _fn in written:
                    yield self.fact_finding(
                        graph.root, rel, wline,
                        f"{fam[1]}.{name} is written into messages here but "
                        "no receiver ever reads it — dead payload on every "
                        "send", wtext)
            elif not written and not read and name not in referenced:
                yield self.fact_finding(
                    graph.root, def_rel, line,
                    f"{fam[1]}.{name} is defined but never written or read "
                    "— dead protocol vocabulary", text)

        # model-version stamping on init/sync paths
        if _VERSION_KEY not in body["keys"]:
            return
        stamping = {(rel, fn) for recs in (u["writes"].get(_VERSION_KEY, ()),)
                    for rel, _l, _t, fn in recs}
        for name, recs in sorted(u["sends"].items()):
            if not any(mark in name for mark in _STAMPED_SENDS):
                continue
            for rel, line, text, fn in recs:
                if (rel, fn) not in stamping:
                    yield self.fact_finding(
                        graph.root, rel, line,
                        f"{fam[1]}.{name} send does not stamp "
                        f"{_VERSION_KEY} in {fn or '<module>'}() — async "
                        "staleness weighting needs the version tag on every "
                        "init/sync broadcast", text)
