"""``hot-span``: serving hot loops keep their telemetry spans (ported from
tools/check_serving.py, PR 6).

The serving hot paths — the continuous-batching engine's admit/step loop
and the gateway's forward path — must time themselves through
``tel.timed(``/``tel.span(`` (perf_counter-based): an uninstrumented hot
loop is how the r05 endpoint collapse (14.5 tok/s against a 370k tok/s
chip) stayed invisible until a full bench window. The registry below names
the functions that MUST contain a span call; deleting the instrumentation
— or renaming a registered function/file without updating the registry —
is a finding (silently skipping a stale entry would let a rename drop the
guard).
"""

from __future__ import annotations

import ast
import os

from ..core import Finding, Rule
from ._util import matches_file

#: (serving-relative file, qualified function) -> must contain tel.timed/span
HOT_LOOPS: tuple = (
    ("continuous_batching.py", "ContinuousBatchingEngine._admit_all"),
    ("continuous_batching.py", "ContinuousBatchingEngine._step_chunk"),
    ("continuous_batching.py", "PagedContinuousBatchingEngine._admit_all"),
    ("continuous_batching.py", "PagedContinuousBatchingEngine._stage_prefill"),
    ("replica_controller.py", "InferenceGateway.predict"),
)

_SPAN_ATTRS = ("timed", "span")
_SERVING_DIR = "fedml_tpu/serving"


def _calls_span(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            if sub.func.attr in _SPAN_ATTRS:
                return True
    return False


class HotSpanRule(Rule):
    id = "hot-span"
    severity = "error"
    description = ("registered serving hot loop lost its tel.timed()/"
                   "tel.span() instrumentation (or the registry went stale)")

    # Per-file checks live in check_file (not finalize) so the incremental
    # engine can serve them from cache: finalize only sees dirty files.
    def check_file(self, ctx):
        repo_serving = os.path.join(ctx.root, *_SERVING_DIR.split("/"))
        in_repo_layout = os.path.isdir(repo_serving)
        for rel, fn_name in HOT_LOOPS:
            target = f"{_SERVING_DIR}/{rel}" if in_repo_layout else rel
            if matches_file(ctx.relpath, target):
                yield from self._check_fn(ctx, rel, fn_name)

    def finalize(self, run):
        # only the missing-FILE check needs whole-run context, and it must
        # be cache-safe: consult the filesystem, not run.files
        repo_serving = os.path.join(run.root, *_SERVING_DIR.split("/"))
        in_repo_layout = os.path.isdir(repo_serving)
        findings = []
        for rel in sorted({rel for rel, _fn in HOT_LOOPS}):
            missing = (os.path.join(repo_serving, rel) if in_repo_layout
                       else os.path.join(run.root, rel))
            if os.path.exists(missing):
                continue
            findings.append(Finding(
                rule=self.id, severity=self.severity, path=missing,
                relpath=os.path.relpath(missing, run.root).replace(os.sep, "/"),
                line=0, col=0,
                message=f"registry names missing file {rel}"))
        return findings

    def _check_fn(self, ctx, rel, fn_name):
        cls_name, _, meth = fn_name.rpartition(".")
        if cls_name:
            scopes = [n for n in ast.walk(ctx.tree)
                      if isinstance(n, ast.ClassDef) and n.name == cls_name]
        else:
            scopes = [ctx.tree]
        found = False
        for scope in scopes:
            nodes = scope.body if cls_name else ast.walk(scope)
            for node in nodes:
                if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and node.name == meth):
                    found = True
                    if not _calls_span(node):
                        yield self.make(
                            ctx, node,
                            f"hot loop {fn_name}() has no tel.timed()/"
                            "tel.span() — wrap the device-touching section "
                            "in tel.timed('serving....') so TTFT/TPOT "
                            "regressions show up in /metrics, not in bench "
                            "windows")
        if not found:
            yield self.make(
                ctx, 0, f"registry names missing function {fn_name}()")


def _fn_calls(node: ast.AST):
    """Callable names invoked anywhere inside ``node``: bare names and the
    trailing attribute of method calls (``self._admission.check`` -> check)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            if isinstance(sub.func, ast.Name):
                yield sub.func.id
            elif isinstance(sub.func, ast.Attribute):
                yield sub.func.attr


class AdmissionRejectRule(Rule):
    id = "admission-reject"
    severity = "error"
    description = ("admission-path reject does not emit the labeled "
                   "fedml_serving_admission_rejected_total{tenant=,reason=} "
                   "counter")

    # A reject site is any construction of AdmissionError. The labeled
    # family has exactly one emission helper — admission.count_reject() —
    # and one indirect emitter: AdmissionController.check(), which counts
    # internally before returning the shed reason. Every function that
    # builds an AdmissionError must call one of the two; an uncounted
    # reject is a request that vanished from the tenant's dashboard.
    _EMITTERS = ("count_reject", "check")

    def check_file(self, ctx):
        if "serving" not in ctx.relpath.replace(os.sep, "/").split("/"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            rejects = [
                sub for sub in ast.walk(node)
                if isinstance(sub, ast.Call)
                and ((isinstance(sub.func, ast.Name)
                      and sub.func.id == "AdmissionError")
                     or (isinstance(sub.func, ast.Attribute)
                         and sub.func.attr == "AdmissionError"))
            ]
            if not rejects:
                continue
            if any(name in self._EMITTERS for name in _fn_calls(node)):
                continue
            for sub in rejects:
                yield self.make(
                    ctx, sub,
                    f"{node.name}() sheds a request (AdmissionError) without "
                    "emitting fedml_serving_admission_rejected_total — route "
                    "the reject through admission.count_reject(tenant, "
                    "reason) (or AdmissionController.check, which counts "
                    "internally) so shed traffic stays visible per tenant")
