"""``raw-delta-escape``: a comm-boundary send whose payload reaches an
unmasked client delta (ISSUE 20).

The privacy subsystem's contract is that once ``args.privacy`` enables
SecAgg, a client's trained weights leave the process only *sanctioned* —
masked into a window's ring (``core/privacy``), run through the comm
compressor (whose payload the server-side fold treats as opaque), or
explicitly routed through ``outbound_delta`` (which raises under
``privacy=secagg`` when handed a raw tree). A new uplink site that attaches
raw trained weights to a ``MODEL_PARAMS`` message would silently bypass all
of it — the mask-off path still trains, so nothing functional catches the
leak.

This rule mirrors the interproc walk (``rules/interproc.py``): per-file
fact collection over ``msg.add_params(<model-params-key>, payload)`` sites
plus the function-local dataflow feeding them, then a finalize pass that
resolves helpers through the project call graph. A payload is *sanctioned*
when it flows through

* a sanctioner by name (``outbound_delta``, ``compress_upload``,
  ``masked_uplink_payload``, anything matching ``*mask*`` / ``*quantize*``
  — the masking entry points), or
* a project helper **all** of whose returns are themselves sanctioned
  (e.g. a ``_mask_upload`` that returns ``outbound_delta(...)`` or None) —
  the one-hop call-graph propagation, so renaming the helper does not blind
  the rule, or
* ``get_global_model_params`` — the *published* global model is
  post-aggregation output, not any client's delta.

Downlink broadcasts (message-type constants named ``*S2C*``) are out of
scope: the server sending the global model toward clients is not a client
delta escaping. So is the transport layer
(``core/distributed/communication``, the ``delta-transport-modules``
option): backends reassemble/echo whatever Message they were handed —
chunk reassembly, S3 rehydration, the comm bench's echo server — which the
*origination* site already sanctioned; flagging the re-attachment would
just bury the real boundary in pragmas. Everything else that attaches the
model-params key —
including sends whose message type the rule cannot resolve — must justify
itself with a reasoned suppression, which is how the split-learning shard
upload (unmasked by design; no SecAgg integration on that front) is
carried.
"""

from __future__ import annotations

import ast
import fnmatch

from ..core import ProjectRule
from ._util import dotted

_DEFAULT_SANCTIONERS = (
    "outbound_delta",
    "compress_upload",
    "masked_uplink_payload",
    "*mask*",
    "*quantize*",
    "get_global_model_params",
)


def _key_arg(node):
    """("lit", s) or ("ref", dotted) for an add_params key argument."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return ("lit", node.value)
    d = dotted(node)
    if d:
        return ("ref", d)
    return None


def _payload_arg(node):
    if isinstance(node, ast.Name):
        return ("name", node.id)
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        return ("call", d) if d else ("other", None)
    if isinstance(node, ast.Attribute):
        d = dotted(node)
        return ("attr", d) if d else ("other", None)
    return ("other", None)


def _return_desc(node):
    v = node.value
    if v is None or (isinstance(v, ast.Constant) and v.value is None):
        return ("none", None)
    if isinstance(v, ast.Call):
        d = dotted(v.func)
        return ("call", d) if d else ("other", None)
    if isinstance(v, ast.Name):
        return ("name", v.id)
    return ("other", None)


class RawDeltaEscapeRule(ProjectRule):
    id = "raw-delta-escape"
    severity = "error"
    description = ("comm-boundary send attaches a client delta that never "
                   "passed through masking/compression/outbound_delta — a "
                   "raw update would leave the process unprotected even "
                   "under privacy=secagg")

    def __init__(self):
        self.sanctioners: tuple = _DEFAULT_SANCTIONERS
        self.delta_key = "model_params"
        self.transport_modules: tuple = (
            "fedml_tpu/core/distributed/communication/*",)

    def configure(self, options):
        pats = options.get("delta-sanctioners")
        if pats:
            self.sanctioners = tuple(pats)
        self.delta_key = options.get("delta-key", self.delta_key)
        transport = options.get("delta-transport-modules")
        if transport is not None:
            self.transport_modules = tuple(transport)

    def _sanctioned_name(self, name):
        if not name:
            return False
        tail = name.split(".")[-1]
        return any(fnmatch.fnmatch(name, p) or fnmatch.fnmatch(tail, p)
                   for p in self.sanctioners)

    # ------------------------------------------------------------------
    def collect(self, ctx):
        sends = []
        assigns = {}     # qual -> [[tgt, callee_dotted|None, line], ...]
        returns = {}     # qual -> [[kind, value], ...]

        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qual = ctx.qualname(fn)
            msg_types = {}   # local var -> message-type ref string
            fn_assigns = []
            fn_returns = []
            fn_sends = []
            for node in ast.walk(fn):
                if ctx.enclosing_function(node) is not fn:
                    continue
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    tgt = node.targets[0].id
                    callee = None
                    if isinstance(node.value, ast.Call):
                        callee = dotted(node.value.func)
                        if callee and callee.split(".")[-1] == "Message" \
                                and node.value.args:
                            tref = dotted(node.value.args[0])
                            if tref:
                                msg_types[tgt] = tref
                    fn_assigns.append([tgt, callee, node.lineno])
                elif isinstance(node, ast.Return):
                    fn_returns.append(list(_return_desc(node)))
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "add_params" \
                        and len(node.args) >= 2:
                    key = _key_arg(node.args[0])
                    if key is None:
                        continue
                    p_kind, p_val = _payload_arg(node.args[1])
                    recv = node.func.value
                    tref = msg_types.get(
                        recv.id if isinstance(recv, ast.Name) else "", "")
                    fn_sends.append([qual, key[0], key[1], p_kind, p_val,
                                     tref, node.lineno,
                                     ctx.raw_line(node.lineno)])
            if fn_sends:
                sends.extend(fn_sends)
                # dataflow facts only matter for functions that send
                assigns[qual] = fn_assigns
                returns[qual] = fn_returns
            elif fn_returns:
                # every function contributes its returns: it may be the
                # sanctioning helper a send in another file resolves to
                returns[qual] = fn_returns
                if fn_assigns:
                    assigns[qual] = fn_assigns

        if not sends and not returns:
            return None
        return {"sends": sends, "assigns": assigns, "returns": returns}

    # ------------------------------------------------------------------
    def _helper_quals(self, graph, facts):
        """(rel, qual) of every function all of whose returns are
        sanctioned: None, a sanctioner call, or a name assigned from one."""
        helpers = set()
        for rel, f in facts.items():
            for qual, rets in (f.get("returns") or {}).items():
                if not rets:
                    continue
                clean_names = {
                    tgt for tgt, callee, _line
                    in (f.get("assigns") or {}).get(qual) or ()
                    if callee and self._sanctioned_name(callee)}
                ok = True
                saw_sanctioned = False
                for kind, value in rets:
                    if kind == "none":
                        continue
                    if kind == "call" and self._sanctioned_name(value):
                        saw_sanctioned = True
                    elif kind == "name" and value in clean_names:
                        saw_sanctioned = True
                    else:
                        ok = False
                        break
                if ok and saw_sanctioned:
                    helpers.add((rel, qual))
        return helpers

    def _payload_clean(self, graph, rel, qual, f, helpers,
                       p_kind, p_val, line):
        def call_clean(callee):
            if self._sanctioned_name(callee):
                return True
            target = graph.resolve_call(rel, qual, callee)
            return target in helpers if target else False

        if p_kind == "call":
            return call_clean(p_val)
        if p_kind != "name":
            return False
        clean = False
        for tgt, callee, aline in (f.get("assigns") or {}).get(qual) or ():
            if aline >= line or tgt != p_val:
                continue
            # later assignment wins: a sanctioned rebind cleans the name,
            # an unsanctioned one re-taints it
            clean = bool(callee) and call_clean(callee)
        return clean

    def finalize_project(self, graph, facts):
        helpers = self._helper_quals(graph, facts)
        for rel, f in sorted(facts.items()):
            if any(fnmatch.fnmatch(rel, p) for p in self.transport_modules):
                continue   # below the boundary: reassembles sanctioned sends
            for (qual, key_how, key_val, p_kind, p_val, tref, line,
                 text) in f.get("sends") or ():
                key = key_val if key_how == "lit" \
                    else graph.constant(rel, key_val)
                if key != self.delta_key:
                    continue
                if tref and "S2C" in tref.split(".")[-1]:
                    continue   # downlink broadcast, not an uplink escape
                if self._payload_clean(graph, rel, qual, f, helpers,
                                       p_kind, p_val, line):
                    continue
                shown = p_val or p_kind
                yield self.fact_finding(
                    graph.root, rel, line,
                    f"`{shown}` is attached to a {self.delta_key!r} uplink "
                    "without passing through a sanctioned path (masking, "
                    "compress_upload, outbound_delta, or a helper that "
                    "returns one) — under privacy=secagg this would leak "
                    "the raw client delta; route it through "
                    "core.privacy.outbound_delta or suppress with the "
                    "reason it is safe", text)
