"""Sharding-hygiene rules (ported from tools/check_sharding.py, PR 7).

Over the SERVER scope (``fedml_tpu/core``, ``fedml_tpu/cross_silo``,
``fedml_tpu/simulation``):

* ``sharding-containment`` — ``jax.sharding`` (Mesh / NamedSharding /
  PartitionSpec) may be referenced only by ``core/distributed/mesh.py``,
  ``core/aggregation/sharded.py`` and the device-collective simulator.
  Scattered NamedSharding construction is how layout drift (one module
  sharding dim 0, another replicating the same leaf) stops being
  reviewable. The TRAINER scope (``parallel/``, ``train/``, ``serving/``)
  carries its own GSPMD plumbing and is deliberately out of scope.
* ``device-get`` — ``jax.device_get`` is banned in the privileged sharding
  modules: the only full-model gather is the host broadcast
  materialization (``host_tree``), which rides ``np.asarray`` per dtype
  group and books its bytes via ``record_transfer``. A ``device_get`` of
  sharded params would replicate the model host-side with zero byte
  accounting.

A privileged file that disappears is a finding too: a rename must move the
allowlist, not silently drop the guard.
"""

from __future__ import annotations

import ast
import os

from ..core import Finding, Rule
from ._util import pkg_rel

SERVER_SCOPE = ("core", "cross_silo", "simulation")

ALLOWED_SHARDING_FILES = (
    "core/distributed/mesh.py",
    "core/aggregation/sharded.py",
    # the device-collective SIMULATOR shards stacked clients over its own
    # "agg" mesh — that mesh is the simulation's subject, not server-layout
    # plumbing; the device_get ban applies to it all the same
    "simulation/collective/collective_sim.py",
)


def _in_server_scope(relpath: str) -> bool:
    rel = pkg_rel(relpath)
    return rel.split("/", 1)[0] in SERVER_SCOPE


def _is_allowed(relpath: str) -> bool:
    return pkg_rel(relpath) in ALLOWED_SHARDING_FILES


class ShardingContainmentRule(Rule):
    id = "sharding-containment"
    severity = "error"
    description = ("jax.sharding reference outside the mesh/sharded modules")
    node_types = (ast.Import, ast.ImportFrom, ast.Attribute)

    def applies_to(self, relpath):
        return _in_server_scope(relpath) and not _is_allowed(relpath)

    def check_node(self, node, ctx):
        desc = None
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.sharding" or alias.name.startswith("jax.sharding."):
                    desc = f"import {alias.name}"
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "jax.sharding" or mod.startswith("jax.sharding."):
                names = ", ".join(a.name for a in node.names)
                desc = f"from {mod} import {names}"
        elif isinstance(node, ast.Attribute):
            if (node.attr == "sharding" and isinstance(node.value, ast.Name)
                    and node.value.id == "jax"):
                desc = "jax.sharding attribute access"
        if desc:
            yield self.make(
                ctx, node,
                f"{desc} outside the mesh/sharded modules — go through "
                "core.distributed.mesh / core.aggregation.sharded")

    def finalize(self, run):
        """A privileged file that vanished is a violation (rename must move
        the allowlist). Only meaningful when the scan covers a package-shaped
        tree — require the scope dirs' parent to exist."""
        pkg_root = os.path.join(run.root, "fedml_tpu")
        base = pkg_root if os.path.isdir(pkg_root) else run.root
        if not any(os.path.isdir(os.path.join(base, s)) for s in SERVER_SCOPE):
            return
        for rel in ALLOWED_SHARDING_FILES:
            path = os.path.join(base, *rel.split("/"))
            if not os.path.exists(path):
                yield Finding(
                    rule=self.id, severity=self.severity, path=path,
                    relpath=os.path.relpath(path, run.root).replace(os.sep, "/"),
                    line=0, col=0,
                    message=f"allowlist names missing file {rel}")


class DeviceGetRule(Rule):
    id = "device-get"
    severity = "error"
    description = "jax.device_get inside a privileged sharding module"
    node_types = (ast.Attribute, ast.ImportFrom)

    def applies_to(self, relpath):
        return _is_allowed(relpath)

    def check_node(self, node, ctx):
        desc = None
        if isinstance(node, ast.Attribute) and node.attr == "device_get":
            desc = "device_get attribute access"
        elif isinstance(node, ast.ImportFrom) and (node.module or "") == "jax":
            if any(a.name == "device_get" for a in node.names):
                desc = "from jax import device_get"
        if desc:
            yield self.make(
                ctx, node,
                f"{desc} in a sharding module — the host gather is "
                "host_tree()'s np.asarray per dtype group (byte-booked via "
                "record_transfer), never device_get")
