"""Shared helpers for rule implementations."""

from __future__ import annotations

import ast


def dotted(node: ast.AST) -> str:
    """Render a Name/Attribute chain as ``a.b.c`` ('' when not a chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def pkg_rel(relpath: str, package: str = "fedml_tpu") -> str:
    """Path relative to the package dir, whether the scan root is the repo
    (``fedml_tpu/core/x.py`` -> ``core/x.py``) or the package itself
    (legacy shims pass the package dir — already ``core/x.py``)."""
    prefix = package + "/"
    if relpath.startswith(prefix):
        return relpath[len(prefix):]
    return relpath


def matches_file(relpath: str, target: str) -> bool:
    """True when ``relpath`` names ``target`` (exact or trailing-path match,
    so rules work from both repo-rooted and package-rooted scans)."""
    return relpath == target or relpath.endswith("/" + target)


def is_jit_callable(node: ast.AST) -> bool:
    """True for ``jit`` / ``jax.jit`` / ``pjit`` / ``jax.pjit`` references."""
    if isinstance(node, ast.Name):
        return node.id in ("jit", "pjit")
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return node.attr in ("jit", "pjit") and node.value.id == "jax"
    return False


def param_names(fn: ast.AST) -> set:
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


def const_int_tuple(node: ast.AST):
    """Parse ``0`` / ``(0, 2)`` / ``[0]`` of int constants, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, int)):
                return None
            vals.append(elt.value)
        return tuple(vals)
    return None


def const_str_tuple(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, str)):
                return None
            vals.append(elt.value)
        return tuple(vals)
    return None
