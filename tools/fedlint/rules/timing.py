"""``wall-clock``: no ``time.time()`` durations (ported from
tools/check_timing.py, PR 2).

``time.time()`` follows the wall clock — NTP steps and slew corrupt any
duration computed from it (a negative "aggregate time" poisons runtime fits
and autoscaling). Durations belong to ``fedml_tpu.core.telemetry``
(span/timed/histogram, perf_counter-based). Legitimate uses are
*timestamps* (record fields, DB rows) and *wall deadlines* (timeouts
coordinated with other processes) — suppress with
``# fedlint: disable=wall-clock <which one and why>``.

The legacy ``# wall-clock ok: <reason>`` marker is still honored so the
``tools/check_timing.py`` shim keeps its historical contract.
"""

from __future__ import annotations

import ast

from ..core import Rule

LEGACY_MARKER = "wall-clock ok"


class WallClockRule(Rule):
    id = "wall-clock"
    severity = "error"
    description = ("time.time() used for durations — use telemetry "
                   "span/timed (perf_counter); mark genuine timestamps/"
                   "deadlines with a suppression")
    node_types = (ast.Call,)

    def check_node(self, node, ctx):
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "time"):
            return
        base = func.value
        if not (isinstance(base, ast.Name) and "time" in base.id):
            return
        if LEGACY_MARKER in ctx.raw_line(node.lineno):
            return
        yield self.make(
            ctx, node,
            "unmarked time.time(): durations must use "
            "fedml_tpu.core.telemetry (span/timed/histogram, "
            "perf_counter-based); genuine timestamps/deadlines need "
            "`# fedlint: disable=wall-clock <reason>`",
        )
