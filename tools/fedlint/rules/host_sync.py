"""``host-sync``: device→host round-trips inside hot loops.

A single ``.item()`` / ``np.asarray`` / ``device_get`` per loop iteration
serializes the device stream against the host — at decode cadence that is
the difference between 370k tok/s and 985 tok/s (bench r05). This rule
generalizes check_sharding's device_get ban to every *registered hot
module* (``[tool.fedlint] hot-modules`` in pyproject.toml): inside any
``for``/``while`` loop body (not crossing into nested defs — those are
usually the jitted payload), it flags

* ``.item()`` and ``.block_until_ready()`` calls,
* ``np.asarray(...)`` / ``jax.device_get(...)``,
* ``float()/int()/bool()`` applied to an expression that touches
  ``jnp.``/``jax.`` (host scalarization of a device value).

Legitimate per-loop syncs exist (an EOS check between chunks, a final
drain) — suppress with ``# fedlint: disable=host-sync <why once-per-chunk
is the design>``; the pragma is the reviewable record that the sync is a
decision, not an accident.
"""

from __future__ import annotations

import ast

from ..core import Rule
from ._util import matches_file


class HostSyncRule(Rule):
    id = "host-sync"
    severity = "error"
    description = "device→host sync inside a hot-module loop"
    node_types = (ast.Call,)

    def __init__(self):
        self.hot_modules: tuple = ()

    def configure(self, options):
        mods = options.get("hot-modules")
        if mods:
            self.hot_modules = tuple(mods)

    def applies_to(self, relpath):
        return any(matches_file(relpath, m) or relpath == m
                   for m in self.hot_modules)

    def check_node(self, node, ctx):
        if not ctx.in_loop_strict(node):
            return
        func = node.func
        msg = None
        if isinstance(func, ast.Attribute):
            if func.attr == "item" and not node.args:
                msg = ".item() inside a hot loop — one device→host sync per iteration"
            elif func.attr == "block_until_ready":
                msg = (".block_until_ready() inside a hot loop — serializes "
                       "the device stream every iteration")
            elif (func.attr == "asarray" and isinstance(func.value, ast.Name)
                  and func.value.id in ("np", "numpy", "onp")):
                msg = ("np.asarray() inside a hot loop — materializes the "
                       "array host-side every iteration")
            elif func.attr == "device_get":
                msg = ("device_get inside a hot loop — host gather per "
                       "iteration with zero byte accounting")
        elif isinstance(func, ast.Name):
            if func.id == "device_get":
                msg = ("device_get inside a hot loop — host gather per "
                       "iteration with zero byte accounting")
            elif func.id in ("float", "int", "bool") and len(node.args) == 1:
                touches_device = any(
                    isinstance(n, ast.Name) and n.id in ("jnp", "jax")
                    for n in ast.walk(node.args[0]))
                if touches_device:
                    msg = (f"{func.id}() scalarizes a device value inside a "
                           "hot loop — one blocking transfer per iteration")
        if msg:
            yield self.make(
                ctx, node,
                msg + "; hoist it out of the loop, batch it per chunk, or "
                "record the design decision with "
                "`# fedlint: disable=host-sync <reason>`")
