"""``lock-graph``: cross-file lock-acquisition-order cycles (ISSUE 10).

The PR-5 ``/statusz`` deadlock was exactly this shape: the server manager
held its round lock and called ``statusz.render()``, which took the
sections lock and — in the buggy version — invoked registered section
callbacks *under* it; a callback took the round lock back. Two files,
opposite orders, no single-file rule could see it.

This rule builds the whole-program lock graph:

* **lock identity** — ``self._lock`` in class ``C`` of module ``M`` is the
  node ``M:C._lock``; module-level locks are ``M:_LOCK``.
  ``self._cv = threading.Condition(self._lock)`` canonicalizes to the
  wrapped lock (holding the condition IS holding the lock).
* **edges** — lock A → lock B when code holding A acquires B: directly
  nested ``with`` blocks, calls (resolved through the project call graph,
  ``self.obj.method()`` included, up to three hops deep), and **callback
  registries**: when a function invokes callables iterated out of a
  container that a registrar method stores its parameter into (the
  statusz section registry, comm-handler maps), every callback passed at a
  registration site is a potential callee at the invocation site.
* **finding** — one per strongly-connected component with a cycle, with
  file:line witnesses for each edge.

A deliberate ordering (e.g. a leaf lock never held across calls) gets
``# fedlint: disable=lock-graph <reason>`` on the witness line.
"""

from __future__ import annotations

import ast

from ..core import ProjectRule
from ._util import dotted

_LOCK_FACTORIES = ("Lock", "RLock", "Condition")
_MAX_DEPTH = 3


def _is_lock_ctor(call) -> bool:
    if not isinstance(call, ast.Call):
        return False
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _LOCK_FACTORIES:
        return isinstance(f.value, ast.Name) and f.value.id == "threading"
    return isinstance(f, ast.Name) and f.id in _LOCK_FACTORIES


def _self_attr(node):
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class LockGraphRule(ProjectRule):
    id = "lock-graph"
    severity = "error"
    description = ("cross-file lock-acquisition-order cycle (two code paths "
                   "take the same locks in opposite orders)")

    # ------------------------------------------------------------------
    def collect(self, ctx):
        # lock ids use an '@' placeholder for this module; finalize rewrites
        # it to the dotted module name so identities are repo-global
        # class -> {attr -> canonical lock attr} (Condition aliases folded)
        lock_attrs, aliases = {}, {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                attrs, alias = set(), {}
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and _is_lock_ctor(sub.value):
                        for tgt in sub.targets:
                            attr = _self_attr(tgt)
                            if attr:
                                attrs.add(attr)
                                if sub.value.args:
                                    inner = _self_attr(sub.value.args[0])
                                    if inner:
                                        alias[attr] = inner
                if attrs:
                    lock_attrs[node.name] = attrs
                    aliases[node.name] = alias
        module_locks = set()
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        module_locks.add(tgt.id)

        def canon(cls, attr):
            amap = aliases.get(cls, {})
            seen = set()
            while attr in amap and attr not in seen:
                seen.add(attr)
                attr = amap[attr]
            return attr

        def lock_id(node, cls):
            """Lock id for a with-item / reference, '@' = this module."""
            attr = _self_attr(node)
            if attr is not None and cls and attr in (
                    set(lock_attrs.get(cls, ())) | set(aliases.get(cls, ()))):
                return f"@:{cls}.{canon(cls, attr)}"
            if isinstance(node, ast.Name) and node.id in module_locks:
                return f"@:{node.id}"
            return None

        def_names = {n.name for n in ast.walk(ctx.tree)
                     if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

        functions = {}
        registrars = {}
        invocations = []
        register_calls = []

        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qual = ctx.qualname(fn)
            cls_node = ctx.enclosing_class(fn)
            cls = cls_node.name if cls_node is not None else None
            params = [a.arg for a in fn.args.args if a.arg != "self"]

            def held_at(node):
                out = []
                for anc in ctx.ancestors(node):
                    if anc is fn:
                        break
                    if isinstance(anc, (ast.With, ast.AsyncWith)):
                        for item in anc.items:
                            lid = lock_id(item.context_expr, cls)
                            if lid:
                                out.append(lid)
                return out

            acquires, under, calls = [], {}, []
            container_names = {}   # loop/comprehension names -> container attr
            for node in ast.walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        lid = lock_id(item.context_expr, cls)
                        if lid is None:
                            continue
                        rec = [lid, node.lineno, ctx.raw_line(node.lineno)]
                        acquires.append(rec)
                        for h in held_at(node):
                            under.setdefault(h, {"locks": [], "calls": []})[
                                "locks"].append(rec)
                elif isinstance(node, ast.Call):
                    name = dotted(node.func)
                    if not name:
                        continue
                    if name.endswith("__exit__") or name.endswith("__enter__"):
                        continue
                    rec = [name, node.lineno, ctx.raw_line(node.lineno)]
                    calls.append(rec)
                    for h in held_at(node):
                        under.setdefault(h, {"locks": [], "calls": []})[
                            "calls"].append(rec)
                    # register-site: a call passing a method reference or a
                    # locally-defined function, resolved against registrars
                    # at finalize (plain data args don't count — keeps the
                    # fact tables small)
                    cb_args = [
                        d if d and ("." in d or d in def_names) else ""
                        for d in (dotted(a) for a in node.args)]
                    if any(cb_args):
                        register_calls.append(
                            [name, cb_args, qual, node.lineno])

            def container_of(node):
                """'@:Cls.attr' for self.attr, '@:name' for a bare name."""
                attr = _self_attr(node)
                if attr and cls:
                    return f"@:{cls}.{attr}"
                if isinstance(node, ast.Name):
                    return f"@:{node.id}"
                return None

            # callback-container plumbing
            for node in ast.walk(fn):
                # registrar: <container>[k] = <param> / .append(<param>)
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Subscript):
                            container = container_of(tgt.value)
                            if (container and isinstance(node.value, ast.Name)
                                    and node.value.id in params):
                                registrars[qual] = [
                                    container, params.index(node.value.id)]
                if isinstance(node, ast.Call):
                    f = node.func
                    if (isinstance(f, ast.Attribute) and f.attr == "append"
                            and container_of(f.value) and node.args
                            and isinstance(node.args[0], ast.Name)
                            and node.args[0].id in params):
                        registrars[qual] = [
                            container_of(f.value),
                            params.index(node.args[0].id)]
                # invoker: names bound by iterating the container
                gens = ()
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    gens = ((node.target, node.iter),)
                elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                       ast.GeneratorExp)):
                    gens = tuple((g.target, g.iter) for g in node.generators)
                for target, it in gens:
                    src = it.func.value if (
                        isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Attribute)) else it
                    container = container_of(src)
                    if not container:
                        continue
                    names = [n.id for n in ast.walk(target)
                             if isinstance(n, ast.Name)]
                    for n in names:
                        container_names[n] = container
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in container_names):
                    invocations.append([
                        container_names[node.func.id], node.lineno,
                        ctx.raw_line(node.lineno), held_at(node)])
                # container[k]() direct dispatch
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Subscript)):
                    container = container_of(node.func.value)
                    if container:
                        invocations.append([
                            container, node.lineno,
                            ctx.raw_line(node.lineno), held_at(node)])

            if acquires or under or calls:
                functions[qual] = {"acquires": acquires, "under": under,
                                   "calls": calls}
            if invocations:
                functions.setdefault(qual, {"acquires": [], "under": {},
                                            "calls": []})
                functions[qual]["invocations"] = invocations
                invocations = []

        if not (functions or registrars or register_calls):
            return None
        return {"functions": functions, "registrars": registrars,
                "register_calls": register_calls}

    # ------------------------------------------------------------------
    def finalize_project(self, graph, facts):
        def globalize(relpath, lid):
            mod = graph.files[relpath]["module"] if relpath in graph.files \
                else relpath
            return lid.replace("@:", f"{mod}:", 1)

        # registry: container id -> registered callbacks (rel, qual)
        registrars = {}
        for rel, f in facts.items():
            for qual, (container, idx) in (f.get("registrars") or {}).items():
                registrars[(rel, qual)] = (globalize(rel, container), idx)
        registry = {}
        for rel, f in facts.items():
            for name, args, scope, _line in f.get("register_calls") or ():
                target = graph.resolve_call(rel, scope, name)
                if target is None or target not in registrars:
                    continue
                container, idx = registrars[target]
                if idx < len(args) and args[idx]:
                    cb = graph.resolve_call(rel, scope, args[idx])
                    if cb:
                        registry.setdefault(container, set()).add(cb)

        fn_facts = {(rel, qual): body
                    for rel, f in facts.items()
                    for qual, body in (f.get("functions") or {}).items()}

        memo = {}

        def eff(key, depth):
            """Locks (globalized) this function may acquire, transitively."""
            if depth < 0 or key not in fn_facts:
                return set()
            if key in memo:
                return memo[key]
            memo[key] = set()      # cycle guard
            rel, qual = key
            body = fn_facts[key]
            out = {globalize(rel, lid) for lid, _l, _t in body["acquires"]}
            for name, _l, _t in body["calls"]:
                callee = graph.resolve_call(rel, qual, name)
                if callee:
                    out |= eff(callee, depth - 1)
            for container, _l, _t, _held in body.get("invocations") or ():
                for cb in registry.get(globalize(rel, container), ()):
                    out |= eff(cb, depth - 1)
            memo[key] = out
            return out

        edges = {}   # (src, dst) -> first witness (rel, line, text)

        def edge(src, dst, rel, line, text):
            if src != dst:
                edges.setdefault((src, dst), (rel, line, text))

        for (rel, qual), body in sorted(fn_facts.items()):
            for held, nested in sorted(body["under"].items()):
                src = globalize(rel, held)
                for lid, line, text in nested["locks"]:
                    edge(src, globalize(rel, lid), rel, line, text)
                for name, line, text in nested["calls"]:
                    callee = graph.resolve_call(rel, qual, name)
                    if callee:
                        for dst in sorted(eff(callee, _MAX_DEPTH)):
                            edge(src, dst, rel, line, text)
            for container, line, text, held in body.get("invocations") or ():
                targets = set()
                for cb in registry.get(globalize(rel, container), ()):
                    targets |= eff(cb, _MAX_DEPTH)
                for h in held:
                    for dst in sorted(targets):
                        edge(globalize(rel, h), dst, rel, line, text)

        yield from self._report_cycles(graph, edges)

    # ------------------------------------------------------------------
    def _report_cycles(self, graph, edges):
        adj = {}
        for (src, dst) in edges:
            adj.setdefault(src, set()).add(dst)
            adj.setdefault(dst, set())
        for scc in _sccs(adj):
            cyclic = len(scc) > 1 or any(
                (n, n) in edges for n in scc)
            if not cyclic:
                continue
            scc_set = set(scc)
            witnesses = sorted(
                (src, dst, edges[(src, dst)])
                for (src, dst) in edges
                if src in scc_set and dst in scc_set)
            if not witnesses:
                continue
            detail = "; ".join(
                f"{src} -> {dst} at {w[0]}:{w[1]}"
                for src, dst, w in witnesses)
            rel, line, text = witnesses[0][2]
            yield self.fact_finding(
                graph.root, rel, line,
                f"lock-order cycle between {', '.join(sorted(scc_set))}: "
                f"{detail} — two paths acquire these locks in opposite "
                "orders; impose one global order or drop a lock before the "
                "cross-module call", text)


def _sccs(adj):
    """Tarjan strongly-connected components, iterative."""
    index, low, on_stack = {}, {}, set()
    stack, out, counter = [], [], [0]
    for start in sorted(adj):
        if start in index:
            continue
        work = [(start, iter(sorted(adj[start])))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj[nxt]))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    n = stack.pop()
                    on_stack.discard(n)
                    comp.append(n)
                    if n == node:
                        break
                out.append(sorted(comp))
    return out
