"""``metric-registry``: every ``fedml_*`` Prometheus series emitted anywhere
must be documented in ``docs/observability.md`` and asserted by at least one
test — and documented names must still be emitted (ISSUE 10).

Emission sites are found whole-program because metric names flow through
module constants (``quorum.PARTIAL_COUNTER``), sometimes cross-module
(``quorum_mod.STALE_REJECTED_COUNTER``): the rule resolves Name/Attribute
arguments through the project symbol table. Canonicalization mirrors
``core/telemetry/prom.py``:

* ``tel.counter("a.b")``          → ``fedml_a_b_total``
* prefix counters (``PREFIX + x`` where the prefix constant ends in ``.``,
  e.g. ``jax.compiles.``)         → the collapsed labeled family
  (``fedml_jax_compiles_total``)
* ``tel.histogram("x_seconds")``  → base ``fedml_x_seconds`` (docs/tests may
  name the base or any of ``_bucket``/``_sum``/``_count``)
* gauge triples ``("name", labels, v)`` (inside ``*gauges*`` functions,
  ``gauges=`` kwargs, or ``gauges``-named assignments) → ``fedml_name``
* literal families built by ``_fam("lit", "_suffix")`` in prom.py itself.

Dynamic names that resolve to nothing are skipped, never guessed at.

ISSUE 14 extension — **SLO series resolution**: every tsdb series named in
an SLO spec (the ``dict(name=..., series="...")`` pack rows and literal
``SLOSpec(series=...)`` constructions) must resolve to a series something
actually feeds: a ``tel.counter``/``tel.histogram`` emission (the tsdb hook
mirrors every telemetry sample), a ``record_gauge``/``record_counter``/
``record_observation`` call, or a prefix family (``comm.retry.`` + label)
that a glob spec (``comm.retry.*``) covers. An SLO watching a series nothing
emits would simply never fire — silent monitoring, worse than none.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re

from ..core import ProjectRule
from ._util import dotted

_NAME_RE = re.compile(r"\bfedml_[A-Za-z0-9_]+")
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _canon(name: str) -> str:
    return "fedml_" + re.sub(r"[^A-Za-z0-9_]", "_", name)


def _name_arg(node):
    """Classify a metric-name argument: ("lit", s) / ("ref", dotted) /
    ("prefix", s) / ("prefix_ref", dotted) / None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return ("lit", node.value)
    d = dotted(node)
    if d:
        return ("ref", d)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = node.left
        if isinstance(left, ast.Constant) and isinstance(left.value, str):
            return ("prefix", left.value)
        d = dotted(left)
        if d:
            return ("prefix_ref", d)
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return ("prefix", first.value)
    return None


class MetricRegistryRule(ProjectRule):
    id = "metric-registry"
    severity = "error"
    description = ("fedml_* metric drift: emitted series missing from "
                   "docs/observability.md or from every test, or a "
                   "documented series nothing emits anymore")

    def __init__(self):
        self.doc_path = "docs/observability.md"
        self.tests_dir = "tests"
        self.ignore: tuple = ("fedml_tpu*",)

    def configure(self, options):
        self.doc_path = options.get("metric-doc", self.doc_path)
        self.tests_dir = options.get("metric-tests-dir", self.tests_dir)
        ignore = options.get("metric-doc-ignore")
        if ignore is not None:
            self.ignore = tuple(ignore)

    def _ignored(self, name):
        return any(fnmatch.fnmatch(name, pat) for pat in self.ignore)

    # ------------------------------------------------------------------
    def collect(self, ctx):
        emits = []
        slo_series = []

        def emit(kind, spec, node):
            emits.append([kind, spec[0], spec[1], node.lineno,
                          ctx.raw_line(node.lineno)])

        # module-local wrappers like quorum._counter(name) that just forward
        # the name to tel.counter()/histogram(): calls to them are emissions
        wrappers = {}
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            pnames = {a.arg for a in fn.args.args}
            for ret in ast.walk(fn):
                if not (isinstance(ret, ast.Return)
                        and isinstance(ret.value, ast.Call)):
                    continue
                rf = ret.value.func
                if (isinstance(rf, ast.Attribute)
                        and rf.attr in ("counter", "histogram")
                        and ret.value.args
                        and isinstance(ret.value.args[0], ast.Name)
                        and ret.value.args[0].id in pnames):
                    wrappers[fn.name] = rf.attr

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Name) and f.id in wrappers
                    and node.args):
                spec = _name_arg(node.args[0])
                if spec:
                    emit(wrappers[f.id], spec, node)
            elif isinstance(f, ast.Attribute) and f.attr in (
                    "counter", "histogram") and node.args:
                spec = _name_arg(node.args[0])
                if spec:
                    emit(f.attr, spec, node)
            # direct tsdb feeds: store.record_gauge("lit", v) etc. register
            # the series for SLO resolution (they bypass the telemetry hook)
            elif isinstance(f, ast.Attribute) and f.attr in (
                    "record_gauge", "record_counter",
                    "record_observation") and node.args:
                spec = _name_arg(node.args[0])
                if spec:
                    emit("tsdb", spec, node)
            # SLO spec rows: dict(name=..., series="...") pack entries and
            # literal SLOSpec(series=...) constructions both NAME a series
            # that must resolve to something emitted
            if ((isinstance(f, ast.Name) and f.id in ("dict", "SLOSpec"))
                    or (isinstance(f, ast.Attribute)
                        and f.attr == "SLOSpec")):
                kws = {kw.arg: kw.value for kw in node.keywords if kw.arg}
                sv = kws.get("series")
                # a bare dict() only counts as a spec row when it also
                # carries name= — arbitrary dicts with a series key don't
                is_spec = "name" in kws or not (
                    isinstance(f, ast.Name) and f.id == "dict")
                if (isinstance(sv, ast.Constant)
                        and isinstance(sv.value, str) and is_spec):
                    slo_series.append([sv.value, node.lineno,
                                       ctx.raw_line(node.lineno)])
            elif isinstance(f, ast.Name) and f.id == "_fam" and node.args:
                parts = []
                for a in node.args[:2]:
                    if isinstance(a, ast.Constant) and isinstance(
                            a.value, str):
                        parts.append(a.value)
                    else:
                        parts = None
                        break
                if parts:
                    emit("fam", ("lit", "".join(parts)), node)
            # gauges=[...] kwarg
            for kw in node.keywords:
                if kw.arg == "gauges":
                    for t in ast.walk(kw.value):
                        self._gauge_tuple(t, emit)
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and "gauges" in fn.name:
                for t in ast.walk(fn):
                    self._gauge_tuple(t, emit)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                names = [t.id if isinstance(t, ast.Name) else dotted(t)
                         for t in node.targets]
                if any(n and n.split(".")[-1] == "gauges" for n in names):
                    for t in ast.walk(node.value):
                        self._gauge_tuple(t, emit)
        if not emits and not slo_series:
            return None
        # dedupe (gauges functions scanned via two paths)
        seen, out = set(), []
        for e in emits:
            key = tuple(e[:4])
            if key not in seen:
                seen.add(key)
                out.append(e)
        facts = {"emits": out}
        if slo_series:
            facts["slo_series"] = slo_series
        return facts

    def _gauge_tuple(self, node, emit):
        if (isinstance(node, ast.Tuple) and len(node.elts) == 3
                and isinstance(node.elts[0], ast.Constant)
                and isinstance(node.elts[0].value, str)):
            emit("gauge", ("lit", node.elts[0].value), node)

    # ------------------------------------------------------------------
    def _canonical(self, graph, relpath, kind, how, value):
        """(canonical_name, match_mode) or None; match_mode 'exact',
        'hist' (histogram base), or 'family' (labeled prefix family)."""
        if how in ("ref", "prefix_ref"):
            value = graph.constant(relpath, value)
            if not isinstance(value, str):
                return None
            how = "lit" if how == "ref" else "prefix"
        if kind == "counter":
            if how == "prefix":
                if not value.endswith("."):
                    return None   # unanchored dynamic name; skip
                return (_canon(value[:-1]) + "_total", "family")
            return (_canon(value) + "_total", "exact")
        if kind == "histogram":
            if how == "prefix":
                return None
            return (_canon(value), "hist")
        if kind == "gauge":
            if how != "lit":
                return None
            return (_canon(value), "exact")
        if kind == "fam":
            return ("fedml_" + re.sub(r"[^A-Za-z0-9_]", "_", value), "exact")
        return None

    def finalize_project(self, graph, facts):
        doc_file = os.path.join(graph.root, *self.doc_path.split("/"))
        try:
            with open(doc_file, encoding="utf-8") as f:
                doc_text = f.read()
        except OSError:
            doc_text = None
        doc_names = set(_NAME_RE.findall(doc_text or ""))

        tests_text = ""
        tests_root = os.path.join(graph.root, *self.tests_dir.split("/"))
        if os.path.isdir(tests_root):
            for dirpath, _dirs, files in os.walk(tests_root):
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        try:
                            with open(os.path.join(dirpath, fn),
                                      encoding="utf-8") as fh:
                                tests_text += fh.read()
                        except OSError:
                            pass

        emitted = {}    # canonical -> (mode, first emission site)
        for rel, f in sorted(facts.items()):
            for kind, how, value, line, text in f.get("emits") or ():
                hit = self._canonical(graph, rel, kind, how, value)
                if hit is None:
                    continue
                canonical, mode = hit
                emitted.setdefault(canonical, (mode, rel, line, text))

        def documented(canonical, mode):
            if canonical in doc_names:
                return True
            if mode == "hist":
                return any(canonical + s in doc_names
                           for s in _HIST_SUFFIXES)
            return False

        def tested(canonical, mode):
            if canonical in tests_text:
                return True
            if mode == "hist":
                return any(canonical + s in tests_text
                           for s in _HIST_SUFFIXES)
            return False

        for canonical, (mode, rel, line, text) in sorted(emitted.items()):
            if self._ignored(canonical):
                continue
            if doc_text is not None and not documented(canonical, mode):
                yield self.fact_finding(
                    graph.root, rel, line,
                    f"metric `{canonical}` is emitted here but not "
                    f"documented in {self.doc_path} — every exported series "
                    "gets a row in the observability doc", text)
            if not tested(canonical, mode):
                yield self.fact_finding(
                    graph.root, rel, line,
                    f"metric `{canonical}` is emitted here but asserted by "
                    "no test — add it to the metric-registry test so a "
                    "rename can't silently break dashboards", text)

        # --- SLO series resolution (ISSUE 14) --------------------------
        # every series an SLO spec watches must be fed by SOMETHING: a
        # telemetry counter/histogram (the tsdb hook mirrors each sample),
        # a record_* call, or a prefix family a glob spec covers
        series_reg: set = set()
        prefix_reg: set = set()
        slo_refs = []
        for rel, f in sorted(facts.items()):
            for kind, how, value, _line, _text in f.get("emits") or ():
                if kind not in ("counter", "histogram", "tsdb"):
                    continue
                if how in ("ref", "prefix_ref"):
                    value = graph.constant(rel, value)
                    if not isinstance(value, str):
                        continue
                    how = "lit" if how == "ref" else "prefix"
                if how == "lit":
                    series_reg.add(value)
                elif how == "prefix" and value.endswith("."):
                    prefix_reg.add(value)
            for value, line, text in f.get("slo_series") or ():
                slo_refs.append((rel, value, line, text))

        def series_resolves(series):
            if series in series_reg:
                return True
            if any(series.startswith(p) for p in prefix_reg):
                return True
            if any(ch in series for ch in "*?["):
                if any(fnmatch.fnmatch(s, series) for s in series_reg):
                    return True
                lit = re.split(r"[*?\[]", series, 1)[0]
                if lit and any(p.startswith(lit) or lit.startswith(p)
                               for p in prefix_reg):
                    return True
            if series.startswith("fedml_"):
                return any(series in (_canon(s), _canon(s) + "_total")
                           for s in series_reg)
            return False

        for rel, series, line, text in slo_refs:
            if not series_resolves(series):
                yield self.fact_finding(
                    graph.root, rel, line,
                    f"SLO spec watches series `{series}` but nothing in the "
                    "tree feeds it (no telemetry counter/histogram, no tsdb "
                    "record_* call, no matching prefix family) — the "
                    "burn-rate alert can never fire", text)

        # documented names that nothing emits anymore
        if doc_text is None:
            return
        hist_bases = {c for c, (m, *_r) in emitted.items() if m == "hist"}
        families = {c for c, (m, *_r) in emitted.items() if m == "family"}
        doc_lines = doc_text.splitlines()
        for name in sorted(doc_names):
            if self._ignored(name) or name in emitted:
                continue
            base = name
            for s in _HIST_SUFFIXES:
                if name.endswith(s):
                    base = name[: -len(s)]
                    break
            if base in hist_bases or base in emitted:
                continue
            if any(name == fam or name.startswith(fam[: -len("_total")])
                   for fam in families):
                continue
            line = next((i for i, ln in enumerate(doc_lines, 1)
                         if name in ln), 1)
            yield self.fact_finding(
                graph.root, self.doc_path, line,
                f"documented metric `{name}` is emitted nowhere in the tree "
                "— stale doc row, or the emission was lost in a refactor",
                doc_lines[line - 1] if line <= len(doc_lines) else "")
