"""``label-cardinality``: any Prometheus gauge/counter family labeled by an
unbounded population axis (``rank``, ``client``, ``tenant``) must be
registered with the telemetry cardinality budget — a call to
``TelemetryCardinalityBudget.admit`` / ``get_budget`` in the emitting scope —
or carry a reasoned suppression (ISSUE 19).

Per-rank label values are the classic Prometheus cardinality bomb: a fleet of
a million clients turns one innocent gauge family into a million live series
and takes the scrape endpoint (and whatever ingests it) down with it. The
budget (`core/telemetry/sketches.TelemetryCardinalityBudget`) is the
project's answer: emitters ask ``admit(family, n)`` before exporting labeled
series and degrade to sketch summaries when refused. This rule finds the
emitters that never ask.

Detection mirrors the ``metric-registry`` rule's gauge discovery (3-tuples
``("name", labels, value)`` inside ``*gauges*`` functions / ``gauges=``
kwargs / ``gauges``-named assignments) plus ``register_prefix_family``
registrations, and flags a site when its labels carry one of the risky keys
as a dict-literal key, a ``dict(rank=...)`` keyword, or an f-string/literal
label value derived from them. A site is budget-registered when its
enclosing function (or the module body, for module-level emitters) calls
``.admit(...)`` or resolves the budget via ``get_budget``.
"""

from __future__ import annotations

import ast

from ..core import ProjectRule
from ._util import dotted

RISKY_LABELS = ("rank", "client", "tenant")


def _risky_label_keys(node) -> list:
    """Risky label keys present in a labels expression (dict literal or
    ``dict(...)`` call). Non-literal label expressions return [] — the rule
    never guesses."""
    keys = []
    if isinstance(node, ast.Dict):
        for k in node.keys:
            if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and k.value in RISKY_LABELS):
                keys.append(k.value)
    elif (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "dict"):
        for kw in node.keywords:
            if kw.arg in RISKY_LABELS:
                keys.append(kw.arg)
    return keys


def _scope_is_registered(scope) -> bool:
    """True when the scope body asks the cardinality budget before emitting:
    any ``*.admit(...)`` call or any reference to ``get_budget``."""
    for n in ast.walk(scope):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr == "admit":
                return True
            d = dotted(f)
            if d and d.split(".")[-1] == "get_budget":
                return True
    return False


class LabelCardinalityRule(ProjectRule):
    id = "label-cardinality"
    severity = "error"
    description = ("prom series labeled by rank/client/tenant without a "
                   "cardinality-budget registration: one gauge family times "
                   "a million clients is a scrape-endpoint outage")

    # ------------------------------------------------------------------
    def collect(self, ctx):
        sites = []

        # enclosing-function index: (lineno range) -> FunctionDef node
        funcs = [n for n in ast.walk(ctx.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

        def enclosing(node):
            best = None
            for fn in funcs:
                if fn.lineno <= node.lineno <= (fn.end_lineno or fn.lineno):
                    if best is None or fn.lineno > best.lineno:
                        best = fn  # innermost wins
            return best

        def note(tuple_node):
            if not (isinstance(tuple_node, ast.Tuple)
                    and len(tuple_node.elts) == 3
                    and isinstance(tuple_node.elts[0], ast.Constant)
                    and isinstance(tuple_node.elts[0].value, str)):
                return
            keys = _risky_label_keys(tuple_node.elts[1])
            if not keys:
                return
            scope = enclosing(tuple_node) or ctx.tree
            sites.append([tuple_node.elts[0].value, ",".join(sorted(set(keys))),
                          tuple_node.lineno, ctx.raw_line(tuple_node.lineno),
                          _scope_is_registered(scope)])

        seen_lines = set()

        def note_once(t):
            if not isinstance(t, ast.Tuple):
                return
            key = (t.lineno, t.col_offset)
            if key not in seen_lines:
                seen_lines.add(key)
                note(t)

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                # gauges=[...] kwarg on any call
                for kw in node.keywords:
                    if kw.arg == "gauges":
                        for t in ast.walk(kw.value):
                            note_once(t)
                # register_prefix_family("name", ("tenant", "reason", ...))
                f = node.func
                d = dotted(f)
                if (d and d.split(".")[-1] == "register_prefix_family"
                        and len(node.args) >= 2):
                    labels = node.args[1]
                    risky = []
                    if isinstance(labels, (ast.Tuple, ast.List)):
                        risky = [e.value for e in labels.elts
                                 if isinstance(e, ast.Constant)
                                 and e.value in RISKY_LABELS]
                    if risky:
                        scope = enclosing(node) or ctx.tree
                        name = (node.args[0].value
                                if isinstance(node.args[0], ast.Constant)
                                else dotted(node.args[0]) or "<dynamic>")
                        sites.append([str(name), ",".join(sorted(set(risky))),
                                      node.lineno, ctx.raw_line(node.lineno),
                                      _scope_is_registered(scope)])
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and "gauges" in fn.name:
                for t in ast.walk(fn):
                    note_once(t)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                names = [t.id if isinstance(t, ast.Name) else dotted(t)
                         for t in node.targets]
                if any(n and n.split(".")[-1] == "gauges" for n in names):
                    for t in ast.walk(node.value):
                        note_once(t)
        return {"sites": sites} if sites else None

    # ------------------------------------------------------------------
    def finalize_project(self, graph, facts):
        for rel, f in sorted(facts.items()):
            for name, keys, line, text, registered in f.get("sites") or ():
                if registered:
                    continue
                yield self.fact_finding(
                    graph.root, rel, line,
                    f"series `{name}` is labeled by `{keys}` (an unbounded "
                    "population axis) but the emitting scope never consults "
                    "the telemetry cardinality budget — call "
                    "`sketches.get_budget().admit(family, n)` and degrade "
                    "to a sketch summary on refusal, or suppress with the "
                    "reason the label set is bounded", text)
