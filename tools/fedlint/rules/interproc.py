"""Interprocedural JAX rules: donated-buffer and traced-ness facts
propagated one call-graph hop through project-local helpers (ISSUE 10).

The per-file rules (``donation-misuse``, ``host-sync``, ``retrace-risk``)
stop at function boundaries; both PR-9 donation bugs crossed one. These
three rules share a fact vocabulary collected per file and joined over the
project call graph:

* ``interproc-donation`` — a function that passes its argument into a
  donated ``jax.jit`` position is itself a donor; a function that returns
  ``jax.device_get(arg)`` / ``np.asarray(arg)`` makes a *view* of its
  argument. At any call site (same file or not): reading a name after a
  donor call consumed it — or reading a view after its base was donated —
  is the PR-9 bug, even when the fold and the read are two functions
  apart. Rebinding (``state = fold(state)``) clears the donated name but
  NOT views made from the old value.
* ``interproc-host-sync`` — a helper whose body forces a host sync
  (``.item()``, ``.block_until_ready()``, ``device_get``, ``np.asarray``)
  called from a loop in a configured hot module is a hidden per-iteration
  sync the per-file rule cannot see.
* ``interproc-retrace`` — a helper that branches on a bare parameter
  (``if flag:``) called from inside a jitted function turns the branch
  into a tracer boolean: a concretization error at best, a silent
  per-value retrace behind ``static_argnums`` at worst.
"""

from __future__ import annotations

import ast

from ..core import ProjectRule
from ._util import const_int_tuple, dotted, is_jit_callable, matches_file

_SYNC_ATTRS = ("item", "block_until_ready")
_SYNC_CALLS = ("jax.device_get", "device_get", "np.asarray", "numpy.asarray",
               "np.array", "numpy.array")
_VIEW_CALLS = _SYNC_CALLS


def _jit_donation(call):
    """Donated positions for a ``jax.jit(...)`` call, or None."""
    if not (isinstance(call, ast.Call) and is_jit_callable(call.func)):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return const_int_tuple(kw.value) or ()
        if kw.arg == "donate_argnames":
            return ()   # positional mapping unknown; still a donor marker
    return None


def _fn_params(fn):
    return [a.arg for a in fn.args.posonlyargs + fn.args.args
            if a.arg != "self"]


class _InterprocBase(ProjectRule):
    """Shared per-file fact collection for the three interproc rules."""

    def collect(self, ctx):
        donors = {}          # name/qualname -> [donated positions]
        view_fns = {}        # qualname -> [param positions returned as views]
        sync_fns = {}        # qualname -> idiom string
        branchy = {}         # qualname -> [param position, line]
        jitted_fns = {}      # qualname -> static positions (decorated defs)
        fn_events = {}       # qualname -> ordered events (donation sim)
        fn_params = {}       # qualname -> positional params
        hot_calls = []       # calls inside hot-module loops

        # module-level donors: NAME = jax.jit(fn, donate_argnums=...)
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                pos = _jit_donation(node.value)
                if pos is not None:
                    donors[node.targets[0].id] = list(pos)

        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qual = ctx.qualname(fn)
            params = _fn_params(fn)
            fn_params[qual] = params

            for dec in fn.decorator_list:
                pos = _jit_donation(dec)
                if pos is not None:
                    donors[qual] = list(pos)
                if is_jit_callable(dec) or (
                        isinstance(dec, ast.Call)
                        and is_jit_callable(dec.func)):
                    static = ()
                    if isinstance(dec, ast.Call):
                        for kw in dec.keywords:
                            if kw.arg == "static_argnums":
                                static = const_int_tuple(kw.value) or ()
                    jitted_fns[qual] = list(static)

            events = []
            for node in ast.walk(fn):
                if ctx.enclosing_function(node) is not fn:
                    continue
                if isinstance(node, ast.Call):
                    name = dotted(node.func)
                    if not name:
                        continue
                    if (isinstance(node.func, ast.Attribute)
                            and node.func.attr in _SYNC_ATTRS):
                        sync_fns.setdefault(qual, f".{node.func.attr}()")
                    if name in _SYNC_CALLS or name.endswith(".device_get"):
                        sync_fns.setdefault(qual, f"{name}()")
                    args = [a.id if isinstance(a, ast.Name) else None
                            for a in node.args]
                    tgt = None
                    parent = ctx.parent(node)
                    if (isinstance(parent, ast.Assign)
                            and len(parent.targets) == 1
                            and isinstance(parent.targets[0], ast.Name)):
                        tgt = parent.targets[0].id
                    events.append(["call", node.lineno, node.col_offset,
                                   name, args, tgt,
                                   ctx.raw_line(node.lineno)])
                    if ctx.in_loop_strict(node):
                        hot_calls.append([name, qual, node.lineno,
                                          ctx.raw_line(node.lineno)])
                elif isinstance(node, ast.Return) and node.value is not None:
                    v = node.value
                    if isinstance(v, ast.Call):
                        name = dotted(v.func)
                        if (name in _VIEW_CALLS
                                or name.endswith(".device_get")) and v.args \
                                and isinstance(v.args[0], ast.Name) \
                                and v.args[0].id in params:
                            view_fns.setdefault(qual, []).append(
                                params.index(v.args[0].id))
                elif isinstance(node, ast.If):
                    test = node.test
                    if isinstance(test, ast.UnaryOp) and isinstance(
                            test.op, ast.Not):
                        test = test.operand
                    if isinstance(test, ast.Name) and test.id in params:
                        branchy.setdefault(
                            qual, [params.index(test.id), node.lineno])
                elif isinstance(node, ast.Name):
                    parent = ctx.parent(node)
                    if isinstance(node.ctx, ast.Load):
                        # direct call args are handled by the call event
                        if isinstance(parent, ast.Call) \
                                and node in parent.args:
                            continue
                        events.append(["load", node.lineno, node.col_offset,
                                       node.id, ctx.raw_line(node.lineno)])
                    elif isinstance(node.ctx, ast.Store):
                        events.append(["store", node.lineno, node.col_offset,
                                       node.id])
            events.sort(key=lambda e: (e[1], e[2]))
            if events:
                fn_events[qual] = events

        if not (donors or view_fns or sync_fns or branchy or jitted_fns
                or hot_calls or fn_events):
            return None
        return {"donors": donors, "views": view_fns, "syncs": sync_fns,
                "branchy": branchy, "jitted": jitted_fns,
                "events": fn_events, "params": fn_params,
                "hot_calls": hot_calls}


class InterprocDonationRule(_InterprocBase):
    id = "interproc-donation"
    severity = "error"
    description = ("buffer read after a call chain donated it (PR-9 "
                   "device_get-view-then-donate across functions/files)")

    def finalize_project(self, graph, facts):
        donors = {}     # (rel, name) -> donated positions
        views = {}      # (rel, qual) -> view param positions
        for rel, f in facts.items():
            for name, pos in (f.get("donors") or {}).items():
                donors[(rel, name)] = pos
            for qual, pos in (f.get("views") or {}).items():
                views[(rel, qual)] = pos
        # one-hop propagation: a helper passing its param into a donated
        # position is itself a donor at that param's position
        for rel, f in facts.items():
            for qual, events in (f.get("events") or {}).items():
                params = (f.get("params") or {}).get(qual) or []
                for e in events:
                    if e[0] != "call":
                        continue
                    target = graph.resolve_symbol(rel, e[3])
                    pos = donors.get(target) if target else None
                    if not pos:
                        continue
                    mine = sorted(
                        params.index(a) for i, a in enumerate(e[4])
                        if i in pos and a in params)
                    if mine and (rel, qual) not in donors:
                        donors[(rel, qual)] = mine

        for rel, f in sorted(facts.items()):
            for qual, events in sorted((f.get("events") or {}).items()):
                yield from self._simulate(
                    graph, rel, qual, events, donors, views)

    def _simulate(self, graph, rel, qual, events, donors, views):
        donated = {}     # name -> (donor text, line)
        view_of = {}     # view name -> base name
        stale = {}       # view name -> (donor text, line)
        for e in events:
            if e[0] == "store":
                _k, _l, _c, name = e
                donated.pop(name, None)
                stale.pop(name, None)
                view_of.pop(name, None)
            elif e[0] == "load":
                _k, line, _c, name, text = e
                if name in donated:
                    dtext, dline = donated[name]
                    yield self.fact_finding(
                        graph.root, rel, line,
                        f"`{name}` read after being donated by "
                        f"`{dtext}` (line {dline}) — the buffer was "
                        "surrendered to XLA; reorder the read or drop the "
                        "donation", text)
                elif name in stale:
                    dtext, dline = stale[name]
                    yield self.fact_finding(
                        graph.root, rel, line,
                        f"`{name}` is a device_get/asarray view whose base "
                        f"was later donated by `{dtext}` (line {dline}) — "
                        "the view may alias the surrendered buffer; copy "
                        "before the donating call", text)
            elif e[0] == "call":
                _k, line, _c, name, args, tgt, text = e
                target = graph.resolve_symbol(rel, name)
                dpos = donors.get(target) if target else None
                vtarget = graph.resolve_call(rel, qual, name)
                vpos = views.get(vtarget) if vtarget else None
                for a in args:
                    if a and a in donated:
                        dtext, dline = donated[a]
                        yield self.fact_finding(
                            graph.root, rel, line,
                            f"`{a}` passed to `{name}` after being donated "
                            f"by `{dtext}` (line {dline})", text)
                if dpos:
                    for i in dpos:
                        if i < len(args) and args[i]:
                            base = args[i]
                            donated[base] = (name, line)
                            for v, b in view_of.items():
                                if b == base:
                                    stale[v] = (name, line)
                if tgt:
                    donated.pop(tgt, None)
                    stale.pop(tgt, None)
                    view_of.pop(tgt, None)
                    if vpos:
                        for i in vpos:
                            if i < len(args) and args[i]:
                                view_of[tgt] = args[i]


class InterprocHostSyncRule(_InterprocBase):
    id = "interproc-host-sync"
    severity = "error"
    description = ("hot-module loop calls a project helper that forces a "
                   "host sync (.item()/device_get) every iteration")

    def __init__(self):
        self.hot_modules: tuple = ()

    def configure(self, options):
        mods = options.get("hot-modules")
        if mods:
            self.hot_modules = tuple(mods)

    def _is_hot(self, relpath):
        return any(matches_file(relpath, m) for m in self.hot_modules)

    def collect(self, ctx):
        # every file contributes sync facts; only hot modules need call sites
        return super().collect(ctx)

    def finalize_project(self, graph, facts):
        syncs = {}
        for rel, f in facts.items():
            for qual, idiom in (f.get("syncs") or {}).items():
                syncs[(rel, qual)] = idiom
        for rel, f in sorted(facts.items()):
            if not self._is_hot(rel):
                continue
            for name, scope, line, text in f.get("hot_calls") or ():
                target = graph.resolve_call(rel, scope, name)
                if target is None or target == (rel, scope):
                    continue
                idiom = syncs.get(target)
                if idiom is None:
                    continue
                drel, dqual = target
                yield self.fact_finding(
                    graph.root, rel, line,
                    f"per-iteration call to {dqual}() ({drel}) which forces "
                    f"a host sync via {idiom} — hoist it out of the loop or "
                    "batch the transfer; a hidden sync per step is how the "
                    "r05 decode collapse happened", text)


class InterprocRetraceRule(_InterprocBase):
    id = "interproc-retrace"
    severity = "error"
    description = ("jitted function calls a helper that branches on a bare "
                   "argument — concretization error or silent retrace")

    def finalize_project(self, graph, facts):
        branchy = {}
        for rel, f in facts.items():
            for qual, info in (f.get("branchy") or {}).items():
                branchy[(rel, qual)] = info
        for rel, f in sorted(facts.items()):
            jitted = f.get("jitted") or {}
            for qual, static in sorted(jitted.items()):
                params = (f.get("params") or {}).get(qual) or []
                static_names = {params[i] for i in static if i < len(params)}
                for e in (f.get("events") or {}).get(qual) or ():
                    if e[0] != "call":
                        continue
                    _k, line, _c, name, args, _tgt, text = e
                    target = graph.resolve_call(rel, qual, name)
                    info = branchy.get(target) if target else None
                    if info is None:
                        continue
                    pos, bline = info
                    if pos < len(args) and args[pos] \
                            and args[pos] in static_names:
                        continue   # branch arg is static — legal
                    drel, dqual = target
                    yield self.fact_finding(
                        graph.root, rel, line,
                        f"jitted {qual}() calls {dqual}() ({drel}:{bline}) "
                        "which branches on its bare argument — under trace "
                        "that boolean is a tracer (error) or forces a "
                        "retrace; use lax.cond or mark the arg static", text)
