"""Resilience-idiom rules (ported from tools/check_resilience.py, PR 5):

* ``bare-sleep`` — ``time.sleep()`` outside ``core/resilience/retry.py``
  needs a reason. Hand-rolled ``for attempt in range(n): ... sleep(...)``
  loops are how unbounded, untelemetered retries creep back in — transient
  failures belong to ``fedml_tpu.core.resilience.retry`` (jittered,
  budget-capped, flight-recorder-booked). Legitimate non-retry sleeps
  (chaos injection, polling an external process, rate pacing) get
  ``# fedlint: disable=bare-sleep <which one>``.
* ``orbax`` — orbax checkpointers may be touched only by
  ``fedml_tpu/utils/checkpoint.py``: its async save + watermark commit is
  what makes crash-resume pick a *complete* step; a direct orbax save would
  reintroduce torn checkpoints.

The legacy ``# sleep ok: <reason>`` marker is still honored so the
``tools/check_resilience.py`` shim keeps its historical contract.
"""

from __future__ import annotations

import ast

from ..core import Rule
from ._util import matches_file

LEGACY_MARKER = "sleep ok"
RETRY_HOME = "core/resilience/retry.py"
CHECKPOINT_HOME = "utils/checkpoint.py"


class BareSleepRule(Rule):
    id = "bare-sleep"
    severity = "error"
    description = ("time.sleep() outside the retry module without a reason "
                   "— retries belong to core.resilience.retry")
    node_types = (ast.Call,)

    def applies_to(self, relpath):
        return not matches_file(relpath, RETRY_HOME)

    def check_node(self, node, ctx):
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "sleep"
                and isinstance(func.value, ast.Name)
                and "time" in func.value.id):
            return
        if LEGACY_MARKER in ctx.raw_line(node.lineno):
            return
        yield self.make(
            ctx, node,
            "unmarked time.sleep(): retries belong to "
            "fedml_tpu.core.resilience.retry (jittered, budget-capped); "
            "legitimate non-retry sleeps need "
            "`# fedlint: disable=bare-sleep <reason>`",
        )


class OrbaxContainmentRule(Rule):
    id = "orbax"
    severity = "error"
    description = ("direct orbax use outside utils/checkpoint.py bypasses "
                   "the watermark commit")
    node_types = (ast.Import, ast.ImportFrom, ast.Attribute)

    def applies_to(self, relpath):
        return not matches_file(relpath, CHECKPOINT_HOME)

    def check_node(self, node, ctx):
        hit = False
        if isinstance(node, ast.Import):
            hit = any(a.name == "orbax" or a.name.startswith("orbax.")
                      for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            hit = mod == "orbax" or mod.startswith("orbax.")
        elif isinstance(node, ast.Attribute):
            hit = (node.attr == "CheckpointManager"
                   and isinstance(node.value, ast.Name)
                   and node.value.id == "ocp")
        if hit:
            yield self.make(
                ctx, node,
                "orbax outside utils/checkpoint.py: checkpoint writes go "
                "through fedml_tpu.utils.checkpoint.CheckpointManager "
                "(async save + watermark commit) — a direct orbax save "
                "reintroduces torn checkpoints",
            )
