"""``retrace-risk``: host values captured inside jit traces.

The r05 int8-decode collapse (985 tok/s against a 370k tok/s chip) was a
per-step retrace: a Python value baked into a jitted closure changed every
step, so XLA recompiled every step. This rule flags the capture patterns
that cause exactly that, inside any function that is jit-compiled —
``@jit`` / ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated, or wrapped
via ``name = jax.jit(fn, ...)`` in the same module:

* ``args.<x>`` reads where ``args`` is a free variable — the argparse
  namespace is a mutable grab-bag; every distinct value is a new trace.
  Pass the value as an argument (or hash it into static_argnums).
* closure dict lookups ``cfg["key"]`` on a free lowercase name — same
  failure mode with one more level of indirection. (ALL_CAPS module
  constants are deliberate static baking and are skipped.)
* f-strings formatting a traced parameter — host-side string formatting
  forces concretization at trace time.
* ``if``/``while`` branching on a bare traced parameter — Python control
  flow runs at trace time; use ``lax.cond``/``jnp.where``. (``is None``
  checks, ``.shape``/``.ndim``/``.dtype`` accesses and ``len()`` are
  static under jit and are skipped.)

A jit site that declares ``static_argnums``/``static_argnames`` has
thought about the static/traced split and is exempted wholesale — the
point is to catch the *unconsidered* captures.
"""

from __future__ import annotations

import ast

from ..core import Rule
from ._util import is_jit_callable, param_names

_STATIC_KEYWORDS = ("static_argnums", "static_argnames")
_STATIC_ATTRS = ("shape", "ndim", "dtype", "size", "sharding")


def _jit_site(call: ast.Call):
    """(is_jit, has_static) for a Call node."""
    if is_jit_callable(call.func):
        has_static = any(k.arg in _STATIC_KEYWORDS for k in call.keywords)
        return True, has_static
    # partial(jax.jit, ...) / functools.partial(jit, ...)
    func = call.func
    is_partial = (isinstance(func, ast.Name) and func.id == "partial") or (
        isinstance(func, ast.Attribute) and func.attr == "partial")
    if is_partial and call.args and is_jit_callable(call.args[0]):
        has_static = any(k.arg in _STATIC_KEYWORDS for k in call.keywords)
        return True, has_static
    return False, False


class RetraceRiskRule(Rule):
    id = "retrace-risk"
    severity = "error"
    description = ("host value captured inside a jit trace — every new "
                   "value recompiles")

    def check_file(self, ctx):
        jitted: list = []  # (FunctionDef, has_static)
        defs_by_name: dict = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, node)
                for dec in node.decorator_list:
                    if is_jit_callable(dec):
                        jitted.append((node, False))
                    elif isinstance(dec, ast.Call):
                        is_jit, has_static = _jit_site(dec)
                        if is_jit:
                            jitted.append((node, has_static))
            elif isinstance(node, ast.Call):
                is_jit, has_static = _jit_site(node)
                if is_jit and node.args:
                    wrapped = node.args[0]
                    # peel instrumentation wrappers taking the fn as first
                    # positional arg: jax.jit(tel.track_compiles(run, ...))
                    while isinstance(wrapped, ast.Call) and wrapped.args:
                        wrapped = wrapped.args[0]
                    if (isinstance(wrapped, ast.Name)
                            and wrapped.id in defs_by_name):
                        jitted.append((defs_by_name[wrapped.id], has_static))
        seen = set()
        for fn, has_static in jitted:
            if id(fn) in seen or has_static:
                continue
            seen.add(id(fn))
            yield from self._check_jitted(fn, ctx)

    def _check_jitted(self, fn, ctx):
        params = param_names(fn)
        local_stores = {
            n.id for n in ast.walk(fn)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
        }
        bound = params | local_stores
        for node in ast.walk(fn):
            if node is fn:
                continue
            if isinstance(node, ast.Attribute):
                base = node.value
                if (isinstance(base, ast.Name) and base.id == "args"
                        and "args" not in bound
                        and isinstance(node.ctx, ast.Load)):
                    yield self.make(
                        ctx, node,
                        f"`args.{node.attr}` captured from the enclosing "
                        f"scope inside jitted `{fn.name}` — each new value "
                        "retraces; pass it as a traced argument or bind it "
                        "before the jit boundary")
            elif isinstance(node, ast.Subscript):
                base = node.value
                key = node.slice
                if (isinstance(base, ast.Name) and base.id not in bound
                        and not base.id.isupper()
                        and isinstance(node.ctx, ast.Load)
                        and isinstance(key, ast.Constant)
                        and isinstance(key.value, str)):
                    yield self.make(
                        ctx, node,
                        f"closure dict lookup `{base.id}[{key.value!r}]` "
                        f"inside jitted `{fn.name}` — the value is baked at "
                        "trace time and a changed entry retraces silently")
            elif isinstance(node, ast.JoinedStr):
                for fv in node.values:
                    if not isinstance(fv, ast.FormattedValue):
                        continue
                    names = [n.id for n in ast.walk(fv.value)
                             if isinstance(n, ast.Name) and n.id in params]
                    if names:
                        yield self.make(
                            ctx, node,
                            f"f-string formats traced value(s) "
                            f"{sorted(set(names))} inside jitted "
                            f"`{fn.name}` — host formatting concretizes at "
                            "trace time")
                        break
            elif isinstance(node, (ast.If, ast.While)):
                yield from self._check_branch(node, fn, params, ctx)

    def _check_branch(self, node, fn, params, ctx):
        test = node.test
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return
        for sub in ast.walk(test):
            if not (isinstance(sub, ast.Name) and sub.id in params
                    and isinstance(sub.ctx, ast.Load)):
                continue
            parent = ctx.parent(sub)
            # x.shape / x.ndim / len(x) / isinstance(x, T) are static
            if isinstance(parent, ast.Attribute) and parent.attr in _STATIC_ATTRS:
                continue
            if isinstance(parent, ast.Attribute):
                continue  # any attribute read — give methods the benefit
            if isinstance(parent, ast.Call):
                fname = parent.func.id if isinstance(parent.func, ast.Name) else ""
                if fname in ("len", "isinstance", "getattr", "hasattr"):
                    continue
            if isinstance(parent, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops):
                continue
            kind = "while" if isinstance(node, ast.While) else "if"
            yield self.make(
                ctx, node,
                f"`{kind}` branches on traced parameter `{sub.id}` inside "
                f"jitted `{fn.name}` — Python control flow runs at trace "
                "time (ConcretizationTypeError or silent retrace); use "
                "lax.cond/lax.select/jnp.where")
            return
