"""Telemetry-hygiene rules (ported from tools/check_telemetry.py, PRs 3–4):

* ``reserved-key`` — the reserved ``Message`` header literal belongs ONLY to
  ``core/telemetry/trace_context.py``; everywhere else must reference
  ``trace_context.RESERVED_TELEMETRY_KEY`` / ``Message.MSG_ARG_KEY_TELEMETRY``
  or a payload key will silently collide and be clobbered by ``inject()``.
* ``recorder-kind`` — flight-recorder event-kind literals belong ONLY to
  ``core/telemetry/flight_recorder.py``; ad-hoc producers spelling them
  elsewhere invent look-alike events ``tools/fr_dump.py`` cannot interpret.
* ``excepthook`` — ``sys.excepthook`` / ``threading.excepthook`` may be
  touched ONLY by the flight recorder; a second installer silently drops
  crash dumps depending on import order.

Ported line-substring scans became AST checks (string constants, attribute
accesses, imports) so docstrings that merely *mention* the needles no longer
have to dance around them.
"""

from __future__ import annotations

import ast

# fedlint: disable-file=recorder-kind this module IS the rule's needle table

from ..core import Rule
from ._util import matches_file

# fragment-wise so this module never matches its own rule
RESERVED_KEY = "__" + "telemetry" + "__"
TRACE_CONTEXT = "core/telemetry/trace_context.py"
FLIGHT_RECORDER = "core/telemetry/flight_recorder.py"
RECORDER_KINDS = frozenset({"span_open", "span_close", "comm_send", "comm_recv"})


class ReservedKeyRule(Rule):
    id = "reserved-key"
    severity = "error"
    description = ("raw reserved telemetry header literal outside "
                   "trace_context.py")
    node_types = (ast.Constant,)

    def applies_to(self, relpath):
        return not matches_file(relpath, TRACE_CONTEXT)

    def check_node(self, node, ctx):
        if isinstance(node.value, str) and node.value == RESERVED_KEY:
            yield self.make(
                ctx, node,
                "raw reserved telemetry key: use Message.MSG_ARG_KEY_TELEMETRY "
                "(or trace_context.RESERVED_TELEMETRY_KEY) — payload keys "
                "must never collide with the header",
            )


class RecorderKindRule(Rule):
    id = "recorder-kind"
    severity = "error"
    description = ("flight-recorder event-kind literal outside "
                   "flight_recorder.py")
    node_types = (ast.Constant,)

    def applies_to(self, relpath):
        return not matches_file(relpath, FLIGHT_RECORDER)

    def check_node(self, node, ctx):
        if isinstance(node.value, str) and node.value in RECORDER_KINDS:
            yield self.make(
                ctx, node,
                f"raw recorder event kind {node.value!r}: use the "
                "flight_recorder.EVENT_* constants via record_event/mark/"
                "record_comm — ad-hoc kinds are invisible to tools/fr_dump.py",
            )


class ExcepthookRule(Rule):
    id = "excepthook"
    severity = "error"
    description = "sys/threading excepthook touched outside flight_recorder.py"
    node_types = (ast.Attribute, ast.ImportFrom)

    def applies_to(self, relpath):
        return not matches_file(relpath, FLIGHT_RECORDER)

    def check_node(self, node, ctx):
        hit = False
        if isinstance(node, ast.Attribute):
            hit = (node.attr == "excepthook"
                   and isinstance(node.value, ast.Name)
                   and node.value.id in ("sys", "threading"))
        elif isinstance(node, ast.ImportFrom):
            hit = ((node.module or "") in ("sys", "threading")
                   and any(a.name == "excepthook" for a in node.names))
        if hit:
            yield self.make(
                ctx, node,
                "excepthook outside flight_recorder: crash handling has ONE "
                "owner — use flight_recorder.install()/installed() instead",
            )
