#!/usr/bin/env python3
"""Sharding-hygiene lint (tier-1 enforced; tests/test_sharded_agg.py runs it).

Two rules over the SERVER scope (``fedml_tpu/core``, ``fedml_tpu/cross_silo``,
``fedml_tpu/simulation``):

1. **Mesh plumbing stays contained.** ``jax.sharding`` (Mesh / NamedSharding /
   PartitionSpec) may be imported or referenced only by
   ``core/distributed/mesh.py`` and ``core/aggregation/sharded.py``.
   Everything else in the server scope goes through those two modules' APIs —
   scattered NamedSharding construction is how layout drift (one module
   sharding dim 0, another replicating the same leaf) stops being reviewable.
   The TRAINER scope (``fedml_tpu/parallel``, ``fedml_tpu/train``,
   ``fedml_tpu/serving``) carries its own GSPMD plumbing and is deliberately
   out of scope.

2. **No device_get in the sharding modules.** ``jax.device_get`` is banned in
   the two modules rule 1 privileges: the only full-model gather is the host
   broadcast materialization (``ShardedBucketedAggregator.host_tree``), which
   rides ``np.asarray`` per dtype group and books its bytes via
   ``record_transfer``. A ``device_get`` of sharded params inside the round
   step would replicate the model host-side with zero byte accounting —
   exactly the materialization the sharded server exists to avoid.

Exit status: 0 clean, 1 with violations listed on stdout.
"""

from __future__ import annotations

import ast
import os
import sys

# directories under the scan root that form the server scope
SERVER_SCOPE: tuple[str, ...] = ("core", "cross_silo", "simulation")

# the only files (relative to the scan root) allowed to touch jax.sharding
ALLOWED_SHARDING_FILES: frozenset = frozenset({
    os.path.join("core", "distributed", "mesh.py"),
    os.path.join("core", "aggregation", "sharded.py"),
    # the device-collective SIMULATOR shards stacked clients over its own
    # "agg" mesh — that mesh is the simulation's subject (the Parrot-NCCL
    # topology under test), not server-layout plumbing, so it keeps its
    # jax.sharding access; the device_get ban applies to it all the same
    os.path.join("simulation", "collective", "collective_sim.py"),
})


def _is_jax_sharding_attr(node: ast.AST) -> bool:
    """True for a ``jax.sharding`` attribute chain (``jax.sharding.Mesh``)."""
    return (isinstance(node, ast.Attribute) and node.attr == "sharding"
            and isinstance(node.value, ast.Name) and node.value.id == "jax")


def _sharding_refs(tree: ast.AST) -> list:
    """(lineno, description) of every jax.sharding import or reference."""
    refs = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.sharding" or alias.name.startswith("jax.sharding."):
                    refs.append((node.lineno, f"import {alias.name}"))
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "jax.sharding" or mod.startswith("jax.sharding."):
                names = ", ".join(a.name for a in node.names)
                refs.append((node.lineno, f"from {mod} import {names}"))
        elif _is_jax_sharding_attr(node):
            refs.append((node.lineno, "jax.sharding attribute access"))
    return refs


def _device_get_refs(tree: ast.AST) -> list:
    """(lineno, description) of every jax.device_get reference (attribute or
    ``from jax import device_get``) — conservative: ANY ``.device_get`` attr
    counts, an alias cannot launder the gather."""
    refs = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr == "device_get":
            refs.append((node.lineno, "device_get attribute access"))
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "") == "jax":
                for alias in node.names:
                    if alias.name == "device_get":
                        refs.append((node.lineno, "from jax import device_get"))
    return refs


def _iter_scope_files(root: str):
    for scope in SERVER_SCOPE:
        top = os.path.join(root, scope)
        for dirpath, _dirnames, filenames in os.walk(top):
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def find_violations(root: str) -> list:
    """(path, lineno, message) for every rule break under ``root`` (the
    ``fedml_tpu`` package dir). Missing privileged files are violations too:
    a rename must move the allowlist, not silently drop the guard."""
    violations = []
    for rel in sorted(ALLOWED_SHARDING_FILES):
        if not os.path.exists(os.path.join(root, rel)):
            violations.append((os.path.join(root, rel), 0,
                               f"allowlist names missing file {rel}"))
    for path in _iter_scope_files(root):
        rel = os.path.relpath(path, root)
        with open(path, encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError as e:
                violations.append((path, e.lineno or 0, f"unparseable: {e.msg}"))
                continue
        if rel not in ALLOWED_SHARDING_FILES:
            for lineno, desc in _sharding_refs(tree):
                violations.append(
                    (path, lineno,
                     f"{desc} outside the mesh/sharded modules — go through "
                     "core.distributed.mesh / core.aggregation.sharded"))
        else:
            for lineno, desc in _device_get_refs(tree):
                violations.append(
                    (path, lineno,
                     f"{desc} in a sharding module — the host gather is "
                     "host_tree()'s np.asarray per dtype group (byte-booked "
                     "via record_transfer), never device_get"))
    return violations


def main(argv: list = ()) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = argv[0] if argv else os.path.join(repo, "fedml_tpu")
    violations = find_violations(root)
    for path, lineno, msg in violations:
        print(f"{os.path.relpath(path, repo)}:{lineno}: {msg}")
    if violations:
        print(
            f"\n{len(violations)} sharding-hygiene violation(s). Mesh and "
            "NamedSharding plumbing lives in core/distributed/mesh.py and "
            "core/aggregation/sharded.py only; see tools/check_sharding.py."
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
