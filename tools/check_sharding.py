#!/usr/bin/env python3
"""Sharding-hygiene lint — thin shim over ``tools.fedlint`` (rules:
sharding-containment, device-get).

The AST walker that lived here (PR 7) is now
``tools/fedlint/rules/sharding.py``; this shim preserves the historical
contract — ``find_violations(root)`` tuples, stdout format, exit codes —
for tier-1 callers (tests/test_sharded_agg.py). The server-scope dirs and
the privileged-file allowlist live in the rule module. New callers use
``python -m tools.fedlint``.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.fedlint import api  # noqa: E402
from tools.fedlint.rules.sharding import (  # noqa: E402,F401 (re-export)
    ALLOWED_SHARDING_FILES,
    SERVER_SCOPE,
)

_RULES = ("sharding-containment", "device-get")


def find_violations(root: str) -> list:
    """Legacy shape: (path, lineno, message) — includes syntax errors in
    scope and missing allowlisted files, as the original walker did."""
    result = api.run_rules(root, list(_RULES))
    out = []
    for f in result.findings:
        if f.rule in _RULES:
            out.append((f.path, f.line, f.message))
        elif f.rule == "syntax-error":
            out.append((f.path, f.line, f.message))
    return out


def main(argv: list = ()) -> int:
    root = argv[0] if argv else os.path.join(_REPO, "fedml_tpu")
    violations = find_violations(root)
    for path, lineno, msg in violations:
        print(f"{os.path.relpath(path, _REPO)}:{lineno}: {msg}")
    if violations:
        print(
            f"\n{len(violations)} sharding-hygiene violation(s). Mesh and "
            "NamedSharding plumbing lives in core/distributed/mesh.py and "
            "core/aggregation/sharded.py only; see tools/fedlint/rules/"
            "sharding.py."
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
