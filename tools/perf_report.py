"""Round-time attribution: join span telemetry with the devperf registry.

``/metrics`` says how many seconds each span family consumed and the
devperf section of ``/statusz`` says how close each compiled program ran
to peak — this tool joins the two into the question operators actually
ask: *where did the round's wall time go, and which programs burned the
device time?*

Buckets (first-match, over a CURATED set of non-overlapping leaf spans so
nested wrappers — ``pipeline.*`` around ``client.*``, ``agg.*`` inside
``{prefix}.aggregate`` — never double-count):

- **compute**: device-bound work (client/LLM train steps, aggregation,
  serving decode/prefill, split-learning halves)
- **comm**: model movement (compress/upload/decompress, broadcast,
  receive, paged admit waves)
- **host**: host-side orchestration (cohort sampling, eval, fold)
- **idle**: round wall minus the sum of the above, clamped at zero —
  scheduler gaps, stragglers, anything unspanned

Usage::

    python -m tools.perf_report --metrics http://localhost:9100/metrics \
        --statusz http://localhost:8080/statusz
    python -m tools.perf_report --metrics metrics.txt --snapshot devperf_snapshot.json

Everything network-ish is stdlib urllib; file paths work wherever a URL
does. Pure helpers (``parse_span_seconds``, ``classify_span``,
``attribute``) are import-safe with no jax dependency — tests drive them
on synthetic data.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

_SPAN_SECONDS_RE = re.compile(
    r'^fedml_span_seconds_total\{span="([^"]+)"\}\s+([0-9eE+.\-]+)\s*$')
_SPAN_COUNT_RE = re.compile(
    r'^fedml_span_count_total\{span="([^"]+)"\}\s+([0-9eE+.\-]+)\s*$')

#: curated leaf spans per bucket; ``{p}`` expands to the engine span prefix
_COMPUTE_SPANS = (
    "client.train", "{p}.client_train", "{p}.aggregate", "llm.train",
    "serving.cb.chunk", "serving.cb.prefill",
    "split.client_backward", "split.server_grads",
)
_COMM_SPANS = (
    "client.compress", "client.upload", "server.decompress",
    "server.receive_model", "server.broadcast", "serving.paged.admit_wave",
)
_HOST_SPANS = (
    "{p}.sample", "{p}.eval", "server.eval", "split.fold",
)


def parse_span_seconds(prom_text: str) -> Dict[str, float]:
    """``fedml_span_seconds_total{span=...}`` lines -> {span: seconds}."""
    out: Dict[str, float] = {}
    for line in prom_text.splitlines():
        m = _SPAN_SECONDS_RE.match(line)
        if m:
            out[m.group(1)] = float(m.group(2))
    return out


def parse_span_counts(prom_text: str) -> Dict[str, float]:
    """``fedml_span_count_total{span=...}`` lines -> {span: count}."""
    out: Dict[str, float] = {}
    for line in prom_text.splitlines():
        m = _SPAN_COUNT_RE.match(line)
        if m:
            out[m.group(1)] = float(m.group(2))
    return out


def _bucket_sets(prefix: str) -> List[Tuple[str, frozenset]]:
    def expand(names):
        return frozenset(n.format(p=prefix) for n in names)

    return [("compute", expand(_COMPUTE_SPANS)),
            ("comm", expand(_COMM_SPANS)),
            ("host", expand(_HOST_SPANS))]


def classify_span(name: str, prefix: str = "fedavg") -> Optional[str]:
    """Bucket for a span name, or None when it is a wrapper/detail span
    deliberately left out of attribution (first match wins)."""
    for bucket, names in _bucket_sets(prefix):
        if name in names:
            return bucket
    return None


def attribute(span_seconds: Dict[str, float],
              devperf_snapshot: Optional[Dict[str, Any]] = None,
              *, prefix: str = "fedavg",
              span_counts: Optional[Dict[str, float]] = None,
              top_k: int = 5) -> Dict[str, Any]:
    """Bucket total round wall time and name the top-k programs by device
    time. ``devperf_snapshot`` is ``devperf.snapshot()`` (or the
    ``devperf`` section of /statusz / the profiler-trace JSON dump)."""
    round_span = f"{prefix}.round"
    round_wall = float(span_seconds.get(round_span, 0.0))
    buckets = {"compute": 0.0, "comm": 0.0, "host": 0.0}
    unattributed: Dict[str, float] = {}
    for name, secs in span_seconds.items():
        if name == round_span:
            continue
        bucket = classify_span(name, prefix)
        if bucket is None:
            unattributed[name] = float(secs)
        else:
            buckets[bucket] += float(secs)
    accounted = sum(buckets.values())
    buckets["idle"] = max(0.0, round_wall - accounted)
    rounds = float((span_counts or {}).get(round_span, 0.0))
    report: Dict[str, Any] = {
        "round_span": round_span,
        "round_wall_s": round_wall,
        "rounds": rounds,
        "buckets_s": buckets,
        "buckets_frac": {
            k: (v / round_wall if round_wall > 0 else 0.0)
            for k, v in buckets.items()
        },
        "unattributed_spans": dict(
            sorted(unattributed.items(), key=lambda kv: -kv[1])),
    }
    programs = (devperf_snapshot or {}).get("programs", {})
    ranked = sorted(programs.values(),
                    key=lambda p: -float(p.get("device_seconds", 0.0)))
    report["top_programs"] = [
        {k: p.get(k) for k in ("label", "device_seconds", "mfu",
                               "achieved_flops_per_sec", "flops_source",
                               "roofline_verdict", "steps")}
        for p in ranked[:max(0, int(top_k))]
    ]
    hbm = (devperf_snapshot or {}).get("hbm", {})
    if hbm:
        report["hbm"] = hbm
    return report


def render_text(report: Dict[str, Any]) -> str:
    lines = [f"round span: {report['round_span']}  "
             f"wall={report['round_wall_s']:.3f}s  "
             f"rounds={report['rounds']:.0f}"]
    lines.append("-- wall-time attribution --")
    for bucket in ("compute", "comm", "host", "idle"):
        secs = report["buckets_s"][bucket]
        frac = report["buckets_frac"][bucket]
        lines.append(f"  {bucket:<8} {secs:>10.3f}s  {100.0 * frac:5.1f}%")
    if report.get("top_programs"):
        lines.append("-- top programs by device time --")
        for p in report["top_programs"]:
            mfu = p.get("mfu")
            mfu_s = f"{100.0 * mfu:.2f}%" if isinstance(mfu, (int, float)) else "n/a"
            lines.append(
                f"  {p.get('label', '?'):<16} {float(p.get('device_seconds') or 0.0):>9.3f}s"
                f"  mfu={mfu_s}  {p.get('roofline_verdict') or '?'}"
                f"  [{p.get('flops_source') or '?'}]")
    if report.get("unattributed_spans"):
        lines.append("-- unattributed spans (wrappers/detail, not bucketed) --")
        for name, secs in list(report["unattributed_spans"].items())[:10]:
            lines.append(f"  {name:<32} {secs:>9.3f}s")
    return "\n".join(lines)


def _fetch(source: str) -> str:
    """Read a URL (http/https) or a file path."""
    if source.startswith("http://") or source.startswith("https://"):
        from urllib.request import urlopen

        with urlopen(source, timeout=10) as resp:  # noqa: S310 - operator-supplied
            return resp.read().decode("utf-8", "replace")
    with open(source, encoding="utf-8") as f:
        return f.read()


def _load_devperf(args) -> Optional[Dict[str, Any]]:
    if args.snapshot:
        return json.loads(_fetch(args.snapshot))
    if args.statusz:
        doc = json.loads(_fetch(args.statusz))
        return doc.get("sections", {}).get("devperf")
    return None


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="attribute round wall time across compute/comm/host/idle "
                    "and rank programs by device time")
    ap.add_argument("--metrics", required=True,
                    help="/metrics URL or a saved prometheus text file")
    ap.add_argument("--statusz", help="/statusz URL or saved JSON (devperf section)")
    ap.add_argument("--snapshot", help="devperf_snapshot.json path/URL "
                                       "(overrides --statusz)")
    ap.add_argument("--prefix", default="fedavg",
                    help="engine span prefix (default: fedavg)")
    ap.add_argument("--top-k", type=int, default=5)
    ap.add_argument("--json", action="store_true", help="emit the report as JSON")
    args = ap.parse_args(argv)

    prom_text = _fetch(args.metrics)
    report = attribute(
        parse_span_seconds(prom_text),
        _load_devperf(args),
        prefix=args.prefix,
        span_counts=parse_span_counts(prom_text),
        top_k=args.top_k,
    )
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render_text(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
