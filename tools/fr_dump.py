#!/usr/bin/env python3
"""Pretty-print a flight-recorder crash dump (JSONL written by
fedml_tpu.core.telemetry.flight_recorder).

Usage:
    python tools/fr_dump.py PATH [PATH ...]
    python tools/fr_dump.py --latest [DIR]     # newest dump in DIR
                                               # (default: ~/.fedml_tpu/crash)
    python tools/fr_dump.py --json PATH        # parsed dump as one JSON doc

Renders the meta header, the triggering exception, the SLO alert that
auto-captured the dump (name, transition, observed vs target over the
window, burn rate), the failing span stack (open spans + the error-unwind
trail), the counter snapshot, the trace context, and the event ring as a
timeline (relative seconds, kind, name, fields; ``slo_alert`` breadcrumbs
are called out with their burn-rate math). Exits non-zero on a
missing/unparseable dump so scripts can gate on it.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

DEFAULT_DUMP_DIR = os.path.join("~", ".fedml_tpu", "crash")


def parse_dump(path: str) -> Dict[str, Any]:
    """Parse a dump file into {meta, exception, span_stack, counters,
    histograms, trace, env, events}. Raises ValueError on malformed input."""
    doc: Dict[str, Any] = {"events": []}
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: not JSON: {e}") from e
            kind = rec.get("type")
            if kind == "event":
                doc["events"].append(rec)
            elif kind is not None:
                doc[kind] = rec
            else:
                raise ValueError(f"{path}:{lineno}: record without a type")
    if "meta" not in doc:
        raise ValueError(f"{path}: no meta record — not a flight-recorder dump")
    return doc


def find_latest(dump_dir: str) -> Optional[str]:
    paths = glob.glob(os.path.join(os.path.expanduser(dump_dir), "fr_*.jsonl"))
    return max(paths, key=os.path.getmtime) if paths else None


def _fmt_fields(fields: Optional[Dict[str, Any]]) -> str:
    if not fields:
        return ""
    return " " + " ".join(f"{k}={v}" for k, v in fields.items())


def _fmt_bytes(n: Any) -> str:
    try:
        n = int(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def _fmt_comm(ev: Dict[str, Any]) -> str:
    """comm_send/comm_recv events: direction arrow to the peer + payload size
    (booked by the comm manager via the netlink payload estimator)."""
    fields = dict(ev.get("fields") or {})
    peer = fields.pop("peer", None)
    nbytes = fields.pop("bytes", None)
    arrow = "->" if ev.get("kind") == "comm_send" else "<-"  # fedlint: disable=recorder-kind stdlib-only dump reader: matches EVENT_COMM_SEND without importing fedml_tpu
    parts = []
    if peer is not None:
        parts.append(f"{arrow} peer {peer}")
    if nbytes is not None:
        parts.append(f"[{_fmt_bytes(nbytes)}]")
    return (" " + " ".join(parts) if parts else "") + _fmt_fields(fields)


def _fmt_q(v: Any) -> str:
    try:
        return f"{float(v):.4g}"
    except (TypeError, ValueError):
        return str(v)


def _fmt_window(window_s: Any) -> str:
    try:
        w = float(window_s)
    except (TypeError, ValueError):
        return str(window_s)
    return f"{w / 60:g}m" if w >= 60 else f"{w:g}s"


def _fmt_alert_mark(ev: Dict[str, Any]) -> str:
    """slo_alert breadcrumbs: the burn-rate math inline, so the timeline
    reads "which SLO moved, when, and by how much" without the alert record."""
    fields = dict(ev.get("fields") or {})
    slo = fields.pop("slo", "?")
    transition = fields.pop("transition", "?")
    observed = fields.pop("observed", None)
    target = fields.pop("target", None)
    burn = fields.pop("burn_rate", None)
    window_s = fields.pop("window_s", None)
    out = f" {slo}: {transition}"
    if observed is not None:
        out += f" (observed {observed} vs target {target}"
        if window_s is not None:
            out += f" over {_fmt_window(window_s)}"
        if burn is not None:
            out += f", burn {burn}x"
        out += ")"
    return out + _fmt_fields(fields)


def _fmt_modelwatch_mark(ev: Dict[str, Any]) -> str:
    """modelwatch / modelwatch_quarantine breadcrumbs: the offending ranks
    and norms inline, so the timeline reads "who diverged, when"."""
    fields = dict(ev.get("fields") or {})
    if ev.get("name") == "modelwatch_quarantine":
        rank = fields.pop("rank", "?")
        norm = fields.pop("norm", None)
        z = fields.pop("z", None)
        out = f" rank {rank} quarantined"
        if norm is not None:
            out += f" (norm {norm}, z {z})"
        return out + _fmt_fields(fields)
    rnd = fields.pop("round", None)
    parts = [] if rnd is None else [f"round {rnd}"]
    for key in ("nan", "inf"):
        v = fields.pop(key, 0)
        if v:
            parts.append(f"{key}={v}")
    for key in ("outliers", "quarantined"):
        v = fields.pop(key, None)
        if v:
            parts.append(f"{key}: {','.join(str(r) for r in v)}")
    upd = fields.pop("update_norm", None)
    if upd is not None:
        parts.append(f"|update|={upd}")
    return (" " + " ".join(parts) if parts else "") + _fmt_fields(fields)


def render(doc: Dict[str, Any], out=sys.stdout) -> None:
    meta = doc["meta"]
    w = out.write
    w("=== flight recorder dump ===\n")
    w(f"reason:   {meta.get('reason')}\n")
    w(f"role:     {meta.get('role')}   pid: {meta.get('pid')}   "
      f"schema: v{meta.get('schema')}\n")
    w(f"events:   {meta.get('events')}/{meta.get('capacity')} "
      f"(dropped {meta.get('dropped')})\n")

    exc = doc.get("exception")
    if exc:
        w(f"\n--- exception: {exc.get('class')}: {exc.get('message')}\n")
        for chunk in exc.get("traceback", []):
            w("    " + chunk.replace("\n", "\n    ").rstrip() + "\n")

    alert = doc.get("alert")
    if alert:
        w(f"\n--- alert: {alert.get('slo')} ({alert.get('transition')})\n")
        w(f"    series:   {alert.get('series')}  signal: {alert.get('signal')}\n")
        w(f"    observed: {alert.get('observed')} {alert.get('comparator')} "
          f"target {alert.get('target')} over {_fmt_window(alert.get('window_s'))}\n")
        w(f"    burn rate: {alert.get('burn_rate')}x\n")
        # modelwatch alert context (ledger rows merged by the SLO engine):
        # who was diverging when the alert captured this snapshot
        clients = alert.get("clients")
        if clients:
            w("    clients (by |z|, worst first):\n")
            for row in clients:
                w(f"      rank {row.get('rank'):>4}  norm {str(row.get('norm')):>12}  "
                  f"z {str(row.get('z')):>10}  {row.get('verdict', '?')}\n")
        agg = alert.get("aggregate")
        if agg:
            w(f"    aggregate:{_fmt_fields(agg)}\n")

    trace = doc.get("trace", {}).get("context")
    if trace:
        w(f"\n--- trace: id={trace.get('trace_id')} round={trace.get('round')}\n")

    mesh = doc.get("mesh")
    if mesh:
        w(f"\n--- mesh topology (spec: {mesh.get('configured_spec')}):\n")
        for name, topo in sorted(mesh.get("meshes", {}).items()):
            axes = "x".join(
                f"{a}:{s}" for a, s in zip(topo.get("axis_names", []),
                                           topo.get("axis_sizes", [])))
            w(f"  {name}: [{axes}] {topo.get('n_devices')}x"
              f"{','.join(topo.get('device_kinds', []))}\n")
        shard = mesh.get("shard_bytes_by_device", {})
        if shard:
            w(f"  shard bytes/device: {min(shard.values())}..{max(shard.values())}\n")

    # fleet sketch summary (schema v2+): quantile table + top-k offenders.
    # Older dumps simply predate the section — note it and move on.
    fleet = doc.get("fleet")
    if fleet:
        w(f"\n--- fleet sketches ({fleet.get('observations')} observations, "
          f"~{fleet.get('clients_seen')} distinct clients, "
          f"{_fmt_bytes(fleet.get('sketch_bytes'))}):\n")
        fams = fleet.get("families") or {}
        if fams:
            w(f"  {'family':<16} {'count':>10} {'p50':>10} {'p90':>10} "
              f"{'p99':>10} {'p999':>10}\n")
            for name in sorted(fams):
                row = fams[name]
                w(f"  {name:<16} {row.get('count', 0):>10}"
                  + "".join(f" {_fmt_q(row.get(q)):>10}"
                            for q in ("0.5", "0.9", "0.99", "0.999")) + "\n")
        for key in ("straggler_ratio", "outlier_rate"):
            v = fleet.get(key)
            if v is not None:
                w(f"  {key}: {float(v):.4f}\n")
        offenders = fleet.get("top_offenders") or []
        if offenders:
            w("  top offenders (by cumulative round time):\n")
            for row in offenders:
                w(f"    rank {row.get('rank'):>8}  "
                  f"{_fmt_q(row.get('round_seconds'))}s\n")
        budget = fleet.get("budget")
        if budget:
            w(f"  series budget: {budget.get('live_total')}/{budget.get('max_series')}"
              f" live; degraded: {sorted(budget.get('degraded') or {}) or 'none'}\n")
    elif int(doc.get("meta", {}).get("schema") or 0) < 2:
        w("\n--- fleet sketches: (dump predates the section — schema v1)\n")

    spans = doc.get("span_stack", {}).get("spans", [])
    if spans:
        w("\n--- failing span stack (outermost first):\n")
        for depth, sp in enumerate(spans):
            state = "open" if sp.get("open") else "unwound"
            w(f"  {'  ' * depth}{sp.get('name')} [{state}]"
              f"{_fmt_fields(sp.get('attrs'))}\n")

    counters = doc.get("counters", {}).get("counters", {})
    if counters:
        w("\n--- counters:\n")
        for name in sorted(counters):
            w(f"  {name} = {counters[name]}\n")

    events = doc.get("events", [])
    if events:
        w(f"\n--- last {len(events)} events (oldest first):\n")
        t0 = events[0].get("t_ns", 0)
        for ev in events:
            rel_s = (ev.get("t_ns", 0) - t0) / 1e9
            if ev.get("kind") in ("comm_send", "comm_recv"):  # fedlint: disable=recorder-kind stdlib-only dump reader: matches EVENT_COMM_* without importing fedml_tpu
                detail = _fmt_comm(ev)
            elif ev.get("kind") == "mark" and ev.get("name") == "slo_alert":  # fedlint: disable=recorder-kind stdlib-only dump reader: matches EVENT_MARK without importing fedml_tpu
                detail = _fmt_alert_mark(ev)
            elif ev.get("kind") == "mark" and str(ev.get("name", "")).startswith("modelwatch"):  # fedlint: disable=recorder-kind stdlib-only dump reader: matches EVENT_MARK without importing fedml_tpu
                detail = _fmt_modelwatch_mark(ev)
            else:
                detail = _fmt_fields(ev.get("fields"))
            w(f"  +{rel_s:9.4f}s  {ev.get('kind'):<10} {ev.get('name')}{detail}\n")
    w("\n")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="*", help="dump files to render")
    p.add_argument("--latest", nargs="?", const=DEFAULT_DUMP_DIR, default=None,
                   metavar="DIR", help="render the newest dump in DIR")
    p.add_argument("--json", action="store_true",
                   help="emit the parsed dump as one JSON document")
    args = p.parse_args(argv)

    paths = list(args.paths)
    if args.latest is not None:
        latest = find_latest(args.latest)
        if latest is None:
            print(f"no dumps in {args.latest}", file=sys.stderr)
            return 1
        paths.append(latest)
    if not paths:
        p.print_usage(sys.stderr)
        return 2

    rc = 0
    for path in paths:
        try:
            doc = parse_dump(path)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            rc = 1
            continue
        if args.json:
            json.dump(doc, sys.stdout, indent=2)
            sys.stdout.write("\n")
        else:
            print(f"# {path}")
            render(doc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
