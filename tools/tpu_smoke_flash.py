"""One-off TPU smoke: pallas flash attention fwd+bwd vs einsum on the real chip.

ADVICE r3: the (block_q, 1) lane-dim layouts were only ever run in interpret
mode; this verifies Mosaic accepts them and produces correct grads.
"""
import sys
import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")
from fedml_tpu.ops.flash_attention import flash_attention


def main():
    print("backend:", jax.default_backend(), jax.devices())
    key = jax.random.PRNGKey(0)
    B, Hq, Hkv, T, D = 2, 8, 2, 512, 64
    kq, kk, kv, kg = jax.random.split(key, 4)
    # flash_attention's layout is [B, T, H, D] (flash_attention.py:340)
    q = jax.random.normal(kq, (B, T, Hq, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, T, Hkv, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, T, Hkv, D), jnp.bfloat16)
    do = jax.random.normal(kg, (B, T, Hq, D), jnp.bfloat16)

    def ref(q, k, v):
        G = Hq // Hkv
        kk_ = jnp.repeat(k, G, axis=2)
        vv = jnp.repeat(v, G, axis=2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), kk_.astype(jnp.float32)) / (D ** 0.5)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vv.astype(jnp.float32)).astype(q.dtype)

    out_p = flash_attention(q, k, v, causal=True)
    out_r = ref(q, k, v)
    err_f = jnp.max(jnp.abs(out_p.astype(jnp.float32) - out_r.astype(jnp.float32)))
    print("fwd max err:", float(err_f))

    def loss_p(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True).astype(jnp.float32) * do.astype(jnp.float32))

    def loss_r(q, k, v):
        return jnp.sum(ref(q, k, v).astype(jnp.float32) * do.astype(jnp.float32))

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    errs = [float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))) for a, b in zip(gp, gr)]
    print("bwd max errs (dq,dk,dv):", errs)
    ok = float(err_f) < 0.1 and all(e < 0.5 for e in errs)
    print("SMOKE", "PASS" if ok else "FAIL")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
