"""One-off TPU smoke: pallas flash attention fwd+bwd vs einsum on the real chip.

ADVICE r3: the (block_q, 1) lane-dim layouts were only ever run in interpret
mode. This verifies Mosaic accepts them and produces correct grads — and if
the NARROW layout is rejected (compile error) or wrong, retries in WIDE
mode (FEDML_FLASH_WIDE_STATS=1: stats broadcast over 128 lanes, the
official jax kernel's layout). The winning mode is written to
``.bench_runtime/flash_stats_mode`` so bench.py's llm_pallas stage runs the
kernels in a layout the real compiler has ACCEPTED, instead of degrading
all the way to the xla-einsum headline.
"""
import hashlib
import os
import signal
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODE_PATH = os.path.join(REPO, ".bench_runtime", "flash_stats_mode")
KERNEL_PATH = os.path.join(REPO, "fedml_tpu", "ops", "flash_attention.py")
# per-layout wall budget: one compile + parity on the tunnel. The parent
# kills the child's whole process group on expiry — a hung child must never
# outlive the smoke and contend with the next bench for the chip.
CHILD_TIMEOUT_S = int(os.environ.get("FEDML_SMOKE_CHILD_TIMEOUT", "540"))


def run_parity() -> bool:
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    from fedml_tpu.ops.flash_attention import flash_attention

    print("backend:", jax.default_backend(), jax.devices())
    if jax.default_backend() != "tpu" and os.environ.get("FEDML_SMOKE_ALLOW_CPU") != "1":
        # a PJRT fallback to CPU runs the kernels in interpret mode — parity
        # would trivially pass and record a VACUOUS Mosaic verdict, stamping
        # the smoke as done without the real compiler ever seeing the layout
        print("SMOKE REFUSED: backend is not tpu (set FEDML_SMOKE_ALLOW_CPU=1 "
              "for a local interpret-mode dry run; no verdict is recorded)")
        return False
    key = jax.random.PRNGKey(0)
    B, Hq, Hkv, T, D = 2, 8, 2, 512, 64
    kq, kk, kv, kg = jax.random.split(key, 4)
    # flash_attention's layout is [B, T, H, D] (flash_attention.py)
    q = jax.random.normal(kq, (B, T, Hq, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, T, Hkv, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, T, Hkv, D), jnp.bfloat16)
    do = jax.random.normal(kg, (B, T, Hq, D), jnp.bfloat16)

    def ref(q, k, v):
        G = Hq // Hkv
        kk_ = jnp.repeat(k, G, axis=2)
        vv = jnp.repeat(v, G, axis=2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), kk_.astype(jnp.float32)) / (D ** 0.5)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vv.astype(jnp.float32)).astype(q.dtype)

    out_p = flash_attention(q, k, v, causal=True)
    out_r = ref(q, k, v)
    err_f = jnp.max(jnp.abs(out_p.astype(jnp.float32) - out_r.astype(jnp.float32)))
    print("fwd max err:", float(err_f))

    def loss_p(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True).astype(jnp.float32) * do.astype(jnp.float32))

    def loss_r(q, k, v):
        return jnp.sum(ref(q, k, v).astype(jnp.float32) * do.astype(jnp.float32))

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    errs = [float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))) for a, b in zip(gp, gr)]
    print("bwd max errs (dq,dk,dv):", errs)
    return float(err_f) < 0.1 and all(e < 0.5 for e in errs)


def record_mode(mode: str) -> None:
    """Verdict is '<mode> <kernel sha256>': bench.py ignores a verdict whose
    hash no longer matches the kernel file (stale verdicts say nothing)."""
    with open(KERNEL_PATH, "rb") as f:
        kernel_hash = hashlib.sha256(f.read()).hexdigest()
    os.makedirs(os.path.dirname(MODE_PATH), mode=0o700, exist_ok=True)
    with open(MODE_PATH, "w") as f:
        f.write(f"{mode} {kernel_hash}")
    print(f"flash stats mode -> {mode} ({MODE_PATH})")


def _run_child(env: dict) -> int:
    """Run one layout attempt in its own PROCESS GROUP with a hard timeout,
    and forward a parent SIGTERM (the watcher's outer `timeout`) to the
    group — an orphaned TPU-holding child must never survive the smoke."""
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            env=env, start_new_session=True)

    def _kill_group(*_a):
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass

    def _on_term(*_a):
        _kill_group()
        sys.exit(143)

    prev = signal.signal(signal.SIGTERM, _on_term)
    try:
        return proc.wait(timeout=CHILD_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        _kill_group()
        proc.wait(timeout=10)
        return -9
    finally:
        signal.signal(signal.SIGTERM, prev)


def main():
    if os.environ.get("FEDML_SMOKE_CHILD") == "1":
        # child invocation: just run the parity at the inherited env's mode
        sys.exit(0 if run_parity() else 1)

    # Each layout runs in its OWN subprocess: a Mosaic rejection can poison
    # the process (cached lowering failures), and the wide retry must start
    # clean. The parent only orchestrates.
    for mode in ("narrow", "wide"):
        env = dict(os.environ, FEDML_SMOKE_CHILD="1")
        if mode == "wide":
            env["FEDML_FLASH_WIDE_STATS"] = "1"
        else:
            env.pop("FEDML_FLASH_WIDE_STATS", None)
        print(f"=== smoke attempt: {mode} stats layout ===", flush=True)
        rc = _run_child(env)
        if rc == 0:
            if os.environ.get("FEDML_SMOKE_ALLOW_CPU") == "1":
                print("SMOKE PASS (interpret-mode dry run; no Mosaic verdict recorded)")
            else:
                record_mode(mode)
                print("SMOKE PASS")
            sys.exit(0)
        print(f"{mode} layout FAILED (rc={rc})", flush=True)
    print("SMOKE FAIL (both layouts)")
    sys.exit(1)


if __name__ == "__main__":
    main()
