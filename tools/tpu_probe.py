"""Tunnel liveness probe, shared by bench.py's _probe_backend and
tools/bench_watch.sh — ONE definition so a future probe hardening cannot
land in one caller and not the other.

EXECUTES a jitted op and fetches the result: jax.devices() alone only
exercises the tunnel's control plane, and windows exist where metadata
answers while every compile/execute RPC stalls (2026-07-31: a whole bench
run of stage timeouts behind a "green" devices() probe).

Prints the device kind and exits 0 when compute works; any hang is the
CALLER's job to bound with a timeout (the stall is uninterruptible native
code, so the probe must run as a killable subprocess).
"""
import jax

device = jax.devices()[0]
value = float(jax.jit(lambda x: x * 2.0 + 1.0)(20.5))
assert value == 42.0, f"compute returned {value}, expected 42.0"
print(getattr(device, "device_kind", device))
