#!/usr/bin/env python3
"""Bench regression sentinel over the measured-artifact trajectory.

The watcher (``tools/bench_watch.sh``) banks one ``BENCH_MEASURED_*.json``
per successful ladder run, plus the round-numbered ``BENCH_r0*.json``
baselines — and until now nothing ever *read* the trajectory, so a decaying
rounds/hr or a TTFT tail doubling between runs was invisible. Runs are
stage-isolated, so key sets differ per artifact; for every headline key the
tool therefore compares its newest occurrence on the trajectory against the
most recent PRIOR occurrence (falling back to the ``BENCH_r0*.json`` parsed
baselines for keys measured only once), prints a per-key delta table, and
exits nonzero when any headline regressed by more than ``--threshold``
(default 10%) in its "worse" direction. The ladder's generic ``value``
headline is qualified by its ``metric`` name so short-window and full-ladder
headlines never cross-compare.

Usage::

    python tools/bench_regress.py [--repo DIR] [--threshold 0.10] [--json]

Exit codes: 0 = no regression (or nothing to compare yet), 1 = at least one
headline regressed, 2 = usage error.
"""

from __future__ import annotations

import argparse
import fnmatch
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

# Headline keys and the direction in which a move is an IMPROVEMENT.
# Patterns are fnmatch globs over dot-flattened artifact paths; anything the
# table does not name is informational only (shape strings, platform notes,
# stage sub-docs) and never trips the sentinel.
HEADLINES: Dict[str, str] = {
    "value:*": "higher",                     # ladder headline, metric-qualified
    "*.value:*": "higher",                   # same, nested (short_window etc.)
    "mfu": "higher",
    "fedavg_rounds_per_hr": "higher",
    "decode_tokens_per_sec": "higher",
    "decode_tokens_per_sec_int8": "higher",
    "int8_decode_speedup": "higher",
    "endpoint_decode_tokens_per_sec": "higher",
    "resnet56_steps_per_sec": "higher",
    "resnet56_mfu": "higher",
    "serving_load_tokens_per_sec": "higher",
    "serving_load_ttft_p50_s": "lower",
    "serving_load_ttft_p99_s": "lower",
    "serving_load_tpot_p50_s": "lower",
    "serving_load_tpot_p99_s": "lower",
    "serving_load_p99_ttft_s": "lower",      # ISSUE 16 paged-engine tails
    "serving_load_p99_tpot_s": "lower",
    "kv_pages_per_token": "lower",           # KV HBM efficiency under load
    "serving_load_kv_hbm_ratio": "lower",    # paged/fixed provisioned bytes
    "async_rounds_per_hr.*": "higher",       # per-cohort dict
    "async_flatness_ratio": "higher",
    "agg_clients_per_sec.*": "higher",       # per-engine/K nested dict
    "agg_sharded_clients_per_sec": "higher",
    "agg_wall_s": "lower",
    "ckpt_enqueue_ms": "lower",
    "placement_speedup.*": "higher",
    "link_bw_error_pct": "lower",
    "probe_overhead_pct": "lower",
    "pipeline_overlap_frac": "higher",       # ISSUE 15 stage executor
    "pipeline_speedup": "higher",
    "slo_overhead_pct": "lower",             # ISSUE 14 evaluator guard
    "llm_mfu": "higher",                     # ISSUE 17 devperf registry MFU
    "devperf_overhead_pct": "lower",         # ISSUE 17 registry cost guard
    "modelwatch_overhead_pct": "lower",      # ISSUE 18 fold-stats cost guard
    "fleet_scale_quantile_err_pct": "lower",  # ISSUE 19 sketch accuracy
    "fleet_telemetry_bytes_per_client": "lower",  # ISSUE 19 memory bound
    "secagg_overhead_pct": "lower",          # ISSUE 20 masking+DP cost guard
    "dp_epsilon_spent": "lower",             # ISSUE 20 budget per bench run
    "_llm_pallas.tokens_per_sec": "higher",
    "_llm_pallas.mfu": "higher",
}


def flatten(doc: Any, prefix: str = "") -> Dict[str, float]:
    """Dot-flattened numeric leaves of an artifact (bool excluded).

    A dict carrying both ``metric`` and a numeric ``value`` is a ladder
    headline: its value flattens to ``value:<metric>`` so runs that measured
    DIFFERENT ladder metrics never cross-compare.
    """
    out: Dict[str, float] = {}
    if isinstance(doc, dict):
        metric = doc.get("metric")
        for k, v in doc.items():
            if (k == "value" and isinstance(metric, str)
                    and isinstance(v, (int, float)) and not isinstance(v, bool)):
                out[f"{prefix}value:{metric}"] = float(v)
            else:
                out.update(flatten(v, f"{prefix}{k}."))
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool):
        out[prefix[:-1]] = float(doc)
    return out


def direction_of(key: str) -> Optional[str]:
    for pat, d in HEADLINES.items():
        if fnmatch.fnmatch(key, pat):
            return d
    return None


def load_measured(repo: str) -> List[Tuple[str, Dict[str, float]]]:
    """(path, flat) for every measured artifact, NEWEST first (the stamp in
    the filename is the watcher's capture time and sorts lexically)."""
    paths = sorted(glob.glob(os.path.join(repo, "BENCH_MEASURED_*.json")),
                   reverse=True)
    out = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                out.append((p, flatten(json.load(f))))
        except (OSError, ValueError) as e:
            print(f"bench_regress: unreadable artifact {p}: {e}", file=sys.stderr)
    return out


def load_baselines(repo: str) -> List[Tuple[str, str, float]]:
    """(path, metric, value) from each ``BENCH_r0*.json`` whose capture
    parsed a headline (many were red-tunnel rounds with ``parsed: null``)."""
    out = []
    for p in sorted(glob.glob(os.path.join(repo, "BENCH_r0*.json"))):
        try:
            with open(p, encoding="utf-8") as f:
                parsed = (json.load(f) or {}).get("parsed")
        except (OSError, ValueError):
            continue
        if parsed and parsed.get("metric") and parsed.get("value") is not None:
            out.append((p, str(parsed["metric"]), float(parsed["value"])))
    return out


def compare(repo: str, threshold: float) -> Dict[str, Any]:
    measured = load_measured(repo)
    # key -> [(path, value), ...] newest-first; parsed baselines ride at the
    # tail so a key measured only once still gets a reference point
    series: Dict[str, List[Tuple[str, float]]] = {}
    for p, flat in measured:
        for key, v in flat.items():
            if direction_of(key) is not None:
                series.setdefault(key, []).append((p, v))
    for p, m, v in reversed(load_baselines(repo)):
        for key in (f"value:{m}", m):
            if direction_of(key) is not None:
                series.setdefault(key, []).append((p, v))
                break
    rows: List[Dict[str, Any]] = []
    for key in sorted(series):
        occ = series[key]
        if len(occ) < 2 or occ[1][1] == 0:
            continue
        (new_p, new), (old_p, old) = occ[0], occ[1]
        delta = (new - old) / abs(old)
        direction = direction_of(key)
        worse = -delta if direction == "higher" else delta
        rows.append({
            "key": key,
            "new": new,
            "old": old,
            "at": os.path.basename(new_p),
            "ref": os.path.basename(old_p),
            "delta_pct": round(delta * 100, 2),
            "direction": direction,
            "regressed": worse > threshold,
        })
    return {
        "newest": os.path.basename(measured[0][0]) if measured else None,
        "threshold_pct": threshold * 100,
        "compared": len(rows),
        "regressions": [r for r in rows if r["regressed"]],
        "rows": rows,
    }


def render_table(report: Dict[str, Any]) -> str:
    lines = []
    if not report["newest"]:
        return "bench_regress: no BENCH_MEASURED_*.json artifacts to compare"
    if not report["rows"]:
        return (f"bench_regress: {report['newest']}: no headline key has a "
                "prior occurrence or baseline yet — nothing to compare")
    w = max(len(r["key"]) for r in report["rows"])
    lines.append(f"bench_regress: trajectory through {report['newest']} "
                 f"(threshold {report['threshold_pct']:.0f}%)")
    lines.append(f"  {'key'.ljust(w)}  {'new':>12}  {'prior':>12}  "
                 f"{'delta':>8}  verdict  (newest <- reference)")
    for r in report["rows"]:
        verdict = "REGRESS" if r["regressed"] else "ok"
        arrow = "+" if r["delta_pct"] >= 0 else ""
        lines.append(
            f"  {r['key'].ljust(w)}  {r['new']:>12.4g}  {r['old']:>12.4g}  "
            f"{arrow}{r['delta_pct']:>6.1f}%  {verdict:7}  "
            f"({r['at']} <- {r['ref']})")
    n = len(report["regressions"])
    lines.append(f"  => {n} regression(s) over threshold"
                 if n else "  => no regressions over threshold")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), help="repo root holding BENCH_*.json")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="fractional regression that trips the sentinel")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report instead of a table")
    args = ap.parse_args(argv)
    if args.threshold <= 0:
        ap.error("--threshold must be > 0")
    report = compare(args.repo, args.threshold)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_table(report))
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
