# Namespace package marker so `python -m tools.fedlint` works from the repo
# root. Operational scripts in this directory stay plain scripts.
