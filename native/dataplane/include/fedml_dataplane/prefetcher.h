// Double-buffered batch prefetcher over parallel shards.
//
// N shards with equal n_samples (e.g. images + labels) are batched with one
// shared shuffled permutation per epoch. A background thread gathers the
// next batches into a ring of preassembled buffers while the consumer
// (Python / the trainer) processes the current one — IO and gather overlap
// with device compute, the classic input-pipeline shape tf.data/grain
// provide and the reference's torch DataLoader workers approximate.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "fedml_dataplane/shard.h"

namespace fedml_dataplane {

class Prefetcher {
 public:
  Prefetcher(std::vector<std::shared_ptr<Shard>> shards, uint64_t batch,
             uint64_t seed, int slots = 3, bool drop_last = true);
  ~Prefetcher();

  // Copy the next ready batch into outs[k] (caller-allocated, batch *
  // sample_bytes(k) each). Returns false at end of epoch; the next call
  // starts the next epoch with a fresh permutation. Single consumer: the
  // batch copy runs outside the lock, which is only safe when one thread
  // calls next().
  bool next(void** outs);

  uint64_t batches_per_epoch() const { return batches_per_epoch_; }
  uint64_t batch() const { return batch_; }
  size_t n_arrays() const { return shards_.size(); }
  size_t batch_bytes(size_t k) const { return batch_ * shards_[k]->sample_bytes(); }

 private:
  struct Slot {
    std::vector<std::vector<uint8_t>> bufs;  // one per shard
    bool ready = false;
    bool epoch_end = false;
  };

  void worker();
  void fill_slot(Slot& slot, uint64_t batch_idx);
  void reshuffle(uint64_t epoch);

  std::vector<std::shared_ptr<Shard>> shards_;
  uint64_t batch_;
  uint64_t seed_;
  uint64_t n_;
  uint64_t batches_per_epoch_;
  std::vector<uint64_t> perm_;

  std::vector<Slot> ring_;
  size_t head_ = 0;  // consumer position
  size_t tail_ = 0;  // producer position
  uint64_t produced_ = 0;  // batch index within epoch (producer side)
  uint64_t epoch_ = 0;
  bool stop_ = false;
  std::mutex mu_;
  std::condition_variable cv_producer_, cv_consumer_;
  std::thread thread_;
};

}  // namespace fedml_dataplane
