// Binary sample-shard format + mmap reader.
//
// TPU-native counterpart of the reference's Python data loaders
// (python/fedml/data/data_loader.py): the hot path of host-side input
// pipelines is gather + copy, which Python does per-batch with the GIL
// held. Here shards are mmap'd (zero read syscalls after open) and batch
// gather runs in C++ worker threads (prefetcher.h).
//
// Layout (little-endian):
//   magic   "FDLP"                u8[4]
//   version u32 (=1)
//   dtype   u32 (1=f32, 2=i32, 3=u8, 4=i64)
//   ndim    u32   (includes the leading sample dim)
//   dims    u64[ndim]
//   data    raw row-major payload
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace fedml_dataplane {

enum class DType : uint32_t { f32 = 1, i32 = 2, u8 = 3, i64 = 4 };

size_t dtype_size(DType d);

class Shard {
 public:
  // mmap the file; throws std::runtime_error on format errors.
  explicit Shard(const std::string& path);
  ~Shard();
  Shard(const Shard&) = delete;
  Shard& operator=(const Shard&) = delete;

  uint64_t n_samples() const { return dims_.empty() ? 0 : dims_[0]; }
  size_t sample_bytes() const { return sample_bytes_; }
  const std::vector<uint64_t>& dims() const { return dims_; }
  DType dtype() const { return dtype_; }

  // pointer to sample i's bytes (mmap'd, read-only)
  const uint8_t* sample(uint64_t i) const { return data_ + i * sample_bytes_; }

  static void write(const std::string& path, DType dtype,
                    const std::vector<uint64_t>& dims, const void* data);

 private:
  int fd_ = -1;
  const uint8_t* base_ = nullptr;  // whole mapping
  const uint8_t* data_ = nullptr;  // payload start
  size_t map_len_ = 0;
  size_t sample_bytes_ = 0;
  DType dtype_ = DType::f32;
  std::vector<uint64_t> dims_;
};

}  // namespace fedml_dataplane
