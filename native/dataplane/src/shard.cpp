#include "fedml_dataplane/shard.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <stdexcept>

namespace fedml_dataplane {

namespace {
constexpr char kMagic[4] = {'F', 'D', 'L', 'P'};
constexpr uint32_t kVersion = 1;
}  // namespace

size_t dtype_size(DType d) {
  switch (d) {
    case DType::f32:
    case DType::i32:
      return 4;
    case DType::u8:
      return 1;
    case DType::i64:
      return 8;
  }
  throw std::runtime_error("bad dtype");
}

Shard::Shard(const std::string& path) {
  fd_ = ::open(path.c_str(), O_RDONLY);
  if (fd_ < 0) throw std::runtime_error("shard open failed: " + path);
  struct stat st;
  if (fstat(fd_, &st) != 0) {
    ::close(fd_);
    throw std::runtime_error("shard stat failed: " + path);
  }
  map_len_ = static_cast<size_t>(st.st_size);
  void* m = mmap(nullptr, map_len_, PROT_READ, MAP_PRIVATE, fd_, 0);
  if (m == MAP_FAILED) {
    ::close(fd_);
    throw std::runtime_error("shard mmap failed: " + path);
  }
  base_ = static_cast<const uint8_t*>(m);

  // validation throws leave the object unconstructed (~Shard never runs),
  // so release the mapping + fd here before rethrowing
  try {
    const uint8_t* p = base_;
    if (map_len_ < 16 || std::memcmp(p, kMagic, 4) != 0)
      throw std::runtime_error("bad shard magic: " + path);
    p += 4;
    uint32_t version, dtype, ndim;
    std::memcpy(&version, p, 4); p += 4;
    std::memcpy(&dtype, p, 4); p += 4;
    std::memcpy(&ndim, p, 4); p += 4;
    if (version != kVersion) throw std::runtime_error("bad shard version");
    if (ndim == 0 || ndim > 8) throw std::runtime_error("bad shard ndim");
    if (map_len_ < 16 + size_t(ndim) * 8) throw std::runtime_error("truncated shard header");
    dims_.resize(ndim);
    std::memcpy(dims_.data(), p, size_t(ndim) * 8);
    p += size_t(ndim) * 8;
    dtype_ = static_cast<DType>(dtype);

    sample_bytes_ = dtype_size(dtype_);
    for (uint32_t i = 1; i < ndim; ++i) sample_bytes_ *= dims_[i];
    data_ = p;
    size_t expect = size_t(p - base_) + n_samples() * sample_bytes_;
    if (map_len_ < expect) throw std::runtime_error("truncated shard payload");
  } catch (...) {
    munmap(const_cast<uint8_t*>(base_), map_len_);
    ::close(fd_);
    throw;
  }
}

Shard::~Shard() {
  if (base_) munmap(const_cast<uint8_t*>(base_), map_len_);
  if (fd_ >= 0) ::close(fd_);
}

void Shard::write(const std::string& path, DType dtype,
                  const std::vector<uint64_t>& dims, const void* data) {
  FILE* f = fopen(path.c_str(), "wb");
  if (!f) throw std::runtime_error("shard write open failed: " + path);
  uint32_t version = kVersion, dt = static_cast<uint32_t>(dtype),
           ndim = static_cast<uint32_t>(dims.size());
  size_t total = dtype_size(dtype);
  for (auto d : dims) total *= d;
  bool ok = fwrite(kMagic, 1, 4, f) == 4 && fwrite(&version, 4, 1, f) == 1 &&
            fwrite(&dt, 4, 1, f) == 1 && fwrite(&ndim, 4, 1, f) == 1 &&
            fwrite(dims.data(), 8, dims.size(), f) == dims.size() &&
            (total == 0 || fwrite(data, 1, total, f) == total);
  fclose(f);
  if (!ok) throw std::runtime_error("shard write failed: " + path);
}

}  // namespace fedml_dataplane
