// C ABI for the data plane (ctypes bridge; same pattern as the edge
// engine's c_api.cpp — no pybind11 in this image).
#include <cstring>
#include <memory>
#include <string>
#include <vector>

#include "fedml_dataplane/prefetcher.h"
#include "fedml_dataplane/shard.h"

using fedml_dataplane::DType;
using fedml_dataplane::Prefetcher;
using fedml_dataplane::Shard;

namespace {
thread_local std::string g_error;

struct PrefetcherHandle {
  std::vector<std::shared_ptr<Shard>> shards;
  std::unique_ptr<Prefetcher> pf;
};

template <typename F>
int guarded(F&& f) {
  try {
    f();
    return 0;
  } catch (const std::exception& e) {
    g_error = e.what();
    return -1;
  }
}
}  // namespace

extern "C" {

const char* fdlp_last_error() { return g_error.c_str(); }

int fdlp_write_shard(const char* path, uint32_t dtype, uint32_t ndim,
                     const uint64_t* dims, const void* data) {
  return guarded([&] {
    Shard::write(path, static_cast<DType>(dtype),
                 std::vector<uint64_t>(dims, dims + ndim), data);
  });
}

// Returns ndim and fills dims (caller provides space for >=8), or -1.
int fdlp_shard_info(const char* path, uint32_t* dtype, uint64_t* dims) {
  int ndim = -1;
  int rc = guarded([&] {
    Shard s(path);
    *dtype = static_cast<uint32_t>(s.dtype());
    ndim = static_cast<int>(s.dims().size());
    for (size_t i = 0; i < s.dims().size(); ++i) dims[i] = s.dims()[i];
  });
  return rc == 0 ? ndim : -1;
}

void* fdlp_prefetcher_create(const char** paths, uint32_t n_arrays,
                             uint64_t batch, uint64_t seed, int slots) {
  PrefetcherHandle* h = nullptr;
  int rc = guarded([&] {
    auto holder = std::make_unique<PrefetcherHandle>();
    for (uint32_t i = 0; i < n_arrays; ++i)
      holder->shards.push_back(std::make_shared<Shard>(paths[i]));
    holder->pf = std::make_unique<Prefetcher>(holder->shards, batch, seed, slots);
    h = holder.release();
  });
  return rc == 0 ? h : nullptr;
}

uint64_t fdlp_batches_per_epoch(void* handle) {
  return static_cast<PrefetcherHandle*>(handle)->pf->batches_per_epoch();
}

// Copies the next batch into outs[k]; returns 1 mid-epoch, 0 at epoch end,
// -1 on error.
int fdlp_prefetcher_next(void* handle, void** outs) {
  int more = -1;
  int rc = guarded([&] {
    more = static_cast<PrefetcherHandle*>(handle)->pf->next(outs) ? 1 : 0;
  });
  return rc == 0 ? more : -1;
}

void fdlp_prefetcher_destroy(void* handle) {
  delete static_cast<PrefetcherHandle*>(handle);
}

}  // extern "C"
