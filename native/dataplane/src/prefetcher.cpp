#include "fedml_dataplane/prefetcher.h"

#include <cstring>
#include <stdexcept>

namespace fedml_dataplane {

namespace {
// splitmix64: deterministic, seedable, good enough for shuffling
uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}
}  // namespace

Prefetcher::Prefetcher(std::vector<std::shared_ptr<Shard>> shards,
                       uint64_t batch, uint64_t seed, int slots,
                       bool drop_last)
    : shards_(std::move(shards)), batch_(batch), seed_(seed) {
  if (shards_.empty()) throw std::runtime_error("prefetcher needs >=1 shard");
  n_ = shards_[0]->n_samples();
  for (auto& s : shards_)
    if (s->n_samples() != n_)
      throw std::runtime_error("parallel shards disagree on n_samples");
  if (batch_ == 0 || batch_ > n_) throw std::runtime_error("bad batch size");
  batches_per_epoch_ = drop_last ? n_ / batch_ : (n_ + batch_ - 1) / batch_;
  if (!drop_last && n_ % batch_ != 0)
    throw std::runtime_error("drop_last=false with ragged tail unsupported");

  perm_.resize(n_);
  reshuffle(0);

  ring_.resize(slots);
  for (auto& slot : ring_) {
    slot.bufs.resize(shards_.size());
    for (size_t k = 0; k < shards_.size(); ++k)
      slot.bufs[k].resize(batch_ * shards_[k]->sample_bytes());
  }
  thread_ = std::thread(&Prefetcher::worker, this);
}

Prefetcher::~Prefetcher() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    stop_ = true;
  }
  cv_producer_.notify_all();
  cv_consumer_.notify_all();
  if (thread_.joinable()) thread_.join();
}

void Prefetcher::reshuffle(uint64_t epoch) {
  for (uint64_t i = 0; i < n_; ++i) perm_[i] = i;
  uint64_t s = seed_ ^ (0xa5a5a5a5ULL + epoch * 0x9e3779b9ULL);
  for (uint64_t i = n_ - 1; i > 0; --i) {
    uint64_t j = splitmix64(s) % (i + 1);
    std::swap(perm_[i], perm_[j]);
  }
}

void Prefetcher::fill_slot(Slot& slot, uint64_t batch_idx) {
  uint64_t start = batch_idx * batch_;
  for (size_t k = 0; k < shards_.size(); ++k) {
    const auto& sh = *shards_[k];
    size_t sb = sh.sample_bytes();
    uint8_t* dst = slot.bufs[k].data();
    for (uint64_t b = 0; b < batch_; ++b)
      std::memcpy(dst + b * sb, sh.sample(perm_[start + b]), sb);
  }
}

void Prefetcher::worker() {
  for (;;) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_producer_.wait(lk, [&] { return stop_ || !ring_[tail_].ready; });
    if (stop_) return;
    Slot& slot = ring_[tail_];
    uint64_t idx = produced_;
    bool epoch_end = idx + 1 >= batches_per_epoch_;
    lk.unlock();

    fill_slot(slot, idx);  // gather outside the lock

    lk.lock();
    slot.ready = true;
    slot.epoch_end = epoch_end;
    tail_ = (tail_ + 1) % ring_.size();
    if (epoch_end) {
      produced_ = 0;
      ++epoch_;
      reshuffle(epoch_);
    } else {
      ++produced_;
    }
    lk.unlock();
    cv_consumer_.notify_one();
  }
}

bool Prefetcher::next(void** outs) {
  std::unique_lock<std::mutex> lk(mu_);
  cv_consumer_.wait(lk, [&] { return stop_ || ring_[head_].ready; });
  if (stop_) return false;
  Slot& slot = ring_[head_];
  // copy outside the lock: the producer never touches a slot whose ready
  // flag is still set, and holding mu_ through a multi-MB memcpy would
  // stall the worker's slot publication (the overlap this ring exists for)
  lk.unlock();
  for (size_t k = 0; k < shards_.size(); ++k)
    std::memcpy(outs[k], slot.bufs[k].data(), slot.bufs[k].size());
  lk.lock();
  bool epoch_end = slot.epoch_end;
  slot.ready = false;
  slot.epoch_end = false;
  head_ = (head_ + 1) % ring_.size();
  lk.unlock();
  cv_producer_.notify_one();
  return !epoch_end;
}

}  // namespace fedml_dataplane
