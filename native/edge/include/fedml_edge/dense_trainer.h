// Dense SGD trainer (the MNN/torch-trainer analogue).
//
// Reference: android/fedmlsdk/MobileNN/src/train/FedMLMNNTrainer.cpp (graph
// SGD loop with per-epoch loss/accuracy callbacks) — here the "graph" is a
// dense MLP (hidden ReLU layers + softmax cross-entropy head), which covers
// the reference mobile zoo's LR/LeNet-class workloads for tabular/flattened
// image data.

#ifndef FEDML_EDGE_DENSE_TRAINER_H
#define FEDML_EDGE_DENSE_TRAINER_H

#include "fedml_edge/base_trainer.h"
#include "fedml_edge/dense_model.h"

namespace fedml_edge {

struct DataSet {
  int n = 0;
  int dim = 0;
  int num_classes = 0;
  std::vector<float> x;    // n * dim
  std::vector<int32_t> y;  // n

  // Binary file: int32 n, dim, num_classes; float32 x[n*dim]; int32 y[n].
  bool load(const std::string &path);
  // Deterministic synthetic fallback (same spirit as the Python data zoo's
  // surrogate loaders under zero egress).
  static DataSet synthetic(int n, int dim, int num_classes, uint64_t seed);
};

class FedMLDenseTrainer : public FedMLBaseTrainer {
 public:
  std::string train() override;

  // One epoch over the loaded data; returns mean loss.
  float train_epoch(DenseModel &model, const DataSet &data, int epoch);
  // Accuracy over [0, limit) rows.
  float evaluate(const DenseModel &model, const DataSet &data, int limit) const;

  DenseModel &model() { return model_; }
  DataSet &data() { return data_; }

 private:
  DenseModel model_;
  DataSet data_;
  bool loaded_ = false;

  void ensure_loaded();
};

}  // namespace fedml_edge

#endif  // FEDML_EDGE_DENSE_TRAINER_H
