// Edge model blob: the wire format edge clients and the Python server share.
//
// v1 layout ("FEDT", dense-only, little-endian):
//   int32 magic = 0x46454454
//   int32 n_layers
//   per layer: int32 in_dim, int32 out_dim
//   then float32 weights layer-major: W0 (in x out row-major), b0, W1, b1...
//
// v2 layout ("FEDC", mixed conv/dense):
//   int32 magic = 0x46454443
//   int32 n_layers
//   per layer: int32 kind, in_dim, out_dim, in_h, in_w, in_c, out_c
//     kind 0 = dense (in_dim x out_dim weights, out_dim bias)
//     kind 1 = conv3x3 SAME + ReLU + 2x2 maxpool (stride 2); weights HWIO
//              [3,3,in_c,out_c], bias [out_c]; in_dim/out_dim are the
//              flattened activation sizes (h*w*c), HWC row-major
//   then float32 weights layer-major as in v1.
//
// The Python side maps this onto a flax pytree
// (fedml_tpu/cross_device/codec.py). Reference analogue: the .mnn model file
// exchanged by Beehive (cross_device/server_mnn/fedml_aggregator.py:200-243);
// conv support mirrors the reference mobile engine training LeNet/ResNet20
// graphs (MobileNN/src/train/FedMLMNNTrainer.cpp).

#ifndef FEDML_EDGE_DENSE_MODEL_H
#define FEDML_EDGE_DENSE_MODEL_H

#include <cstdint>
#include <string>
#include <vector>

namespace fedml_edge {

constexpr int32_t kModelMagic = 0x46454454;    // v1 "FEDT"
constexpr int32_t kModelMagicV2 = 0x46454443;  // v2 "FEDC"

enum LayerKind : int32_t { kDense = 0, kConv3x3Pool = 1 };

struct DenseLayer {
  int32_t kind = kDense;
  int32_t in_dim = 0;   // flattened input size
  int32_t out_dim = 0;  // flattened output size
  // conv-only geometry (0 for dense):
  int32_t in_h = 0, in_w = 0, in_c = 0, out_c = 0;
  std::vector<float> w;  // dense: in*out row-major; conv: 3*3*in_c*out_c HWIO
  std::vector<float> b;  // dense: out_dim; conv: out_c

  int out_h() const { return in_h / 2; }  // SAME conv then 2x2 pool
  int out_w() const { return in_w / 2; }
};

struct DenseModel {
  std::vector<DenseLayer> layers;

  int input_dim() const { return layers.empty() ? 0 : layers.front().in_dim; }
  int output_dim() const { return layers.empty() ? 0 : layers.back().out_dim; }
  size_t num_params() const;
  bool has_conv() const;

  // flat view in blob order (W0, b0, W1, b1, ...)
  std::vector<float> flatten() const;
  void unflatten(const std::vector<float> &flat);

  bool save(const std::string &path) const;
  bool load(const std::string &path);

  // Kaiming-ish deterministic init for standalone runs (dense MLP).
  static DenseModel create(const std::vector<int> &dims, uint64_t seed);
  // LeNet-style: conv3x3+pool stages over (in_h, in_w, in_c), then dense
  // layers (hidden dims..., num_classes).
  static DenseModel create_conv(int in_h, int in_w, int in_c,
                                const std::vector<int> &conv_channels,
                                const std::vector<int> &dense_dims, uint64_t seed);
};

}  // namespace fedml_edge

#endif  // FEDML_EDGE_DENSE_MODEL_H
