// Dense model blob: the wire format edge clients and the Python server share.
//
// Layout (little-endian):
//   int32 magic = 0x46454454 ("FEDT")
//   int32 n_layers
//   per layer: int32 in_dim, int32 out_dim
//   then all float32 weights layer-major: W0 (in*out, row-major in-dim x
//   out-dim), b0 (out), W1, b1, ...
//
// The Python side maps this directly onto a flax Dense pytree
// (fedml_tpu/cross_device/codec.py). Reference analogue: the .mnn model file
// exchanged by Beehive (cross_device/server_mnn/fedml_aggregator.py:200-243
// reads/averages/writes MNN files); a flat self-describing blob replaces the
// opaque MNN graph.

#ifndef FEDML_EDGE_DENSE_MODEL_H
#define FEDML_EDGE_DENSE_MODEL_H

#include <cstdint>
#include <string>
#include <vector>

namespace fedml_edge {

constexpr int32_t kModelMagic = 0x46454454;

struct DenseLayer {
  int32_t in_dim = 0;
  int32_t out_dim = 0;
  std::vector<float> w;  // in_dim * out_dim, row-major
  std::vector<float> b;  // out_dim
};

struct DenseModel {
  std::vector<DenseLayer> layers;

  int input_dim() const { return layers.empty() ? 0 : layers.front().in_dim; }
  int output_dim() const { return layers.empty() ? 0 : layers.back().out_dim; }
  size_t num_params() const;

  // flat view in blob order (W0, b0, W1, b1, ...)
  std::vector<float> flatten() const;
  void unflatten(const std::vector<float> &flat);

  bool save(const std::string &path) const;
  bool load(const std::string &path);

  // Kaiming-ish deterministic init for standalone runs.
  static DenseModel create(const std::vector<int> &dims, uint64_t seed);
};

}  // namespace fedml_edge

#endif  // FEDML_EDGE_DENSE_MODEL_H
