// Edge-client training engine: base trainer interface.
//
// Reference: android/fedmlsdk/MobileNN/includes/train/FedMLBaseTrainer.h:14-24
// — same init/train/getEpochAndLoss/stopTraining surface so a client manager
// written against the reference SDK maps 1:1. The backends differ: the
// reference drives MNN or libtorch graph executors; this engine is a
// dependency-free dense SGD core (edge devices train tiny models; the TPU
// side of the framework handles the server/aggregation plane).

#ifndef FEDML_EDGE_BASE_TRAINER_H
#define FEDML_EDGE_BASE_TRAINER_H

#include <cstdint>
#include <functional>
#include <string>
#include <vector>

namespace fedml_edge {

using ProgressCallback = std::function<void(float)>;
using AccuracyCallback = std::function<void(int, float)>;
using LossCallback = std::function<void(int, float)>;

class FedMLBaseTrainer {
 public:
  virtual ~FedMLBaseTrainer() = default;

  // Mirrors FedMLBaseTrainer::init (reference :17-22). modelCachePath /
  // dataCachePath name the serialized model blob and the training data file.
  void init(const char *model_cache_path, const char *data_cache_path,
            const char *dataset, int train_size, int test_size,
            int batch_size, double learning_rate, int epoch_num,
            ProgressCallback progress_cb = nullptr,
            AccuracyCallback accuracy_cb = nullptr,
            LossCallback loss_cb = nullptr);

  // Run local training; returns the path of the updated model blob
  // (reference returns the MNN output path).
  virtual std::string train() = 0;

  // "epoch,loss" of the most recent step (reference :26).
  std::string get_epoch_and_loss() const;

  // Request cooperative stop; returns true (reference :28).
  bool stop_training();

 protected:
  std::string model_cache_path_;
  std::string data_cache_path_;
  std::string dataset_;
  int train_size_ = 0;
  int test_size_ = 0;
  int batch_size_ = 32;
  double learning_rate_ = 0.01;
  int epoch_num_ = 1;

  int cur_epoch_ = 0;
  float cur_loss_ = 0.0f;
  bool stop_flag_ = false;

  ProgressCallback progress_cb_;
  AccuracyCallback accuracy_cb_;
  LossCallback loss_cb_;
};

}  // namespace fedml_edge

#endif  // FEDML_EDGE_BASE_TRAINER_H
