// Reference: android/fedmlsdk/MobileNN/includes/FedMLClientManager.h:6.

#ifndef FEDML_EDGE_CLIENT_MANAGER_H
#define FEDML_EDGE_CLIENT_MANAGER_H

#include "fedml_edge/dense_trainer.h"

namespace fedml_edge {

class FedMLClientManager {
 public:
  FedMLClientManager();
  ~FedMLClientManager();

  void init(const char *model_cache_path, const char *data_cache_path,
            const char *dataset, int train_size, int test_size,
            int batch_size, double learning_rate, int epoch_num,
            ProgressCallback progress_cb = nullptr,
            AccuracyCallback accuracy_cb = nullptr,
            LossCallback loss_cb = nullptr);

  std::string train();
  std::string get_epoch_and_loss() const;
  bool stop_training();

  FedMLDenseTrainer *trainer();

 private:
  FedMLDenseTrainer *trainer_;
};

}  // namespace fedml_edge

#endif  // FEDML_EDGE_CLIENT_MANAGER_H
