// LightSecAgg masking on the edge, GF(p) exact.
//
// Reference: android/fedmlsdk/MobileNN/src/security/LightSecAgg.cpp — the
// same offline/online mask protocol as the Python core
// (fedml_tpu/core/mpc/lightsecagg.py), but the reference's C++ does Lagrange
// algebra in float with std::fmod, which loses exactness for large p. This
// implementation keeps everything in int64 with a proper modular inverse, so
// the server-side Python decoder (lcc_decode) reconstructs edge masks
// bit-exactly.

#ifndef FEDML_EDGE_LIGHT_SECAGG_H
#define FEDML_EDGE_LIGHT_SECAGG_H

#include <cstdint>
#include <vector>

namespace fedml_edge {

// Matches fedml_tpu.core.mpc.finite_field.DEFAULT_PRIME.
constexpr int64_t kDefaultPrime = 2147483647;  // 2^31 - 1

int64_t mod_pow(int64_t base, int64_t exp, int64_t p);
int64_t mod_inverse(int64_t a, int64_t p);

// Lagrange coefficient matrix: coeffs[i][j] = l_j(alpha_i) over GF(p),
// evaluation points beta (the share holders), target points alpha.
std::vector<std::vector<int64_t>> lagrange_coeffs(
    const std::vector<int64_t> &eval_points,
    const std::vector<int64_t> &interp_points, int64_t p);

// Encode payload rows (U x chunk) into one share per client (N x chunk):
// the polynomial through (alpha_i, payload_i) evaluated at each beta_j.
std::vector<std::vector<int64_t>> lcc_encode(
    const std::vector<std::vector<int64_t>> &payload,
    const std::vector<int64_t> &beta, const std::vector<int64_t> &alpha,
    int64_t p);

// Quantize float weights into GF(p) (two's-complement style wrap), matching
// finite_field.quantize / dequantize in the Python core.
std::vector<int64_t> quantize(const std::vector<float> &x, int q_bits, int64_t p);
std::vector<float> dequantize(const std::vector<int64_t> &xq, int q_bits, int64_t p);

struct MaskState {
  std::vector<int64_t> local_mask;                    // d_pad
  std::vector<std::vector<int64_t>> encoded_shares;   // N x chunk
};

// Offline phase (reference LightSecAgg.cpp mask_encoding / Python
// lightsecagg.encode_mask): draw a uniform mask, LCC-encode into N shares.
MaskState encode_mask(int d, int num_clients, int target_active,
                      int privacy_guarantee, int64_t p, uint64_t seed);

// Online phase: y = x + z mod p.
std::vector<int64_t> mask_vector(const std::vector<int64_t> &x_finite,
                                 const MaskState &state, int64_t p);

// Sum received shares over the active set mod p.
std::vector<int64_t> aggregate_encoded_mask(
    const std::vector<std::vector<int64_t>> &received_shares, int64_t p);

}  // namespace fedml_edge

#endif  // FEDML_EDGE_LIGHT_SECAGG_H
