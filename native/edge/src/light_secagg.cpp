#include "fedml_edge/light_secagg.h"

#include <cmath>
#include <random>
#include <stdexcept>

namespace fedml_edge {

namespace {
// Multiplication mod p via __int128 (p < 2^31 so products fit easily, but
// keep it general for larger primes).
inline int64_t mod_mul(int64_t a, int64_t b, int64_t p) {
  return static_cast<int64_t>((static_cast<__int128>(a) * b) % p);
}

inline int64_t mod_norm(int64_t a, int64_t p) {
  int64_t r = a % p;
  return r < 0 ? r + p : r;
}
}  // namespace

int64_t mod_pow(int64_t base, int64_t exp, int64_t p) {
  int64_t result = 1;
  base = mod_norm(base, p);
  while (exp > 0) {
    if (exp & 1) result = mod_mul(result, base, p);
    base = mod_mul(base, base, p);
    exp >>= 1;
  }
  return result;
}

int64_t mod_inverse(int64_t a, int64_t p) {
  // Fermat: p prime, a != 0 mod p.
  a = mod_norm(a, p);
  if (a == 0) throw std::invalid_argument("mod_inverse of 0");
  return mod_pow(a, p - 2, p);
}

std::vector<std::vector<int64_t>> lagrange_coeffs(
    const std::vector<int64_t> &eval_points,
    const std::vector<int64_t> &interp_points, int64_t p) {
  // coeffs[i][j] = prod_{k != j} (eval_i - interp_k) / (interp_j - interp_k)
  const size_t ne = eval_points.size(), ni = interp_points.size();
  std::vector<std::vector<int64_t>> coeffs(ne, std::vector<int64_t>(ni, 0));
  for (size_t i = 0; i < ne; ++i) {
    for (size_t j = 0; j < ni; ++j) {
      int64_t num = 1, den = 1;
      for (size_t k = 0; k < ni; ++k) {
        if (k == j) continue;
        num = mod_mul(num, mod_norm(eval_points[i] - interp_points[k], p), p);
        den = mod_mul(den, mod_norm(interp_points[j] - interp_points[k], p), p);
      }
      coeffs[i][j] = mod_mul(num, mod_inverse(den, p), p);
    }
  }
  return coeffs;
}

std::vector<std::vector<int64_t>> lcc_encode(
    const std::vector<std::vector<int64_t>> &payload,
    const std::vector<int64_t> &beta, const std::vector<int64_t> &alpha,
    int64_t p) {
  auto coeffs = lagrange_coeffs(beta, alpha, p);  // N x U
  const size_t n = beta.size(), u = alpha.size();
  const size_t chunk = payload.empty() ? 0 : payload[0].size();
  std::vector<std::vector<int64_t>> shares(n, std::vector<int64_t>(chunk, 0));
  for (size_t i = 0; i < n; ++i)
    for (size_t j = 0; j < u; ++j) {
      int64_t c = coeffs[i][j];
      if (c == 0) continue;
      for (size_t t = 0; t < chunk; ++t)
        shares[i][t] = mod_norm(shares[i][t] + mod_mul(c, payload[j][t], p), p);
    }
  return shares;
}

std::vector<int64_t> quantize(const std::vector<float> &x, int q_bits, int64_t p) {
  std::vector<int64_t> out(x.size());
  const double scale = static_cast<double>(1LL << q_bits);
  for (size_t i = 0; i < x.size(); ++i) {
    int64_t v = static_cast<int64_t>(std::llround(static_cast<double>(x[i]) * scale));
    out[i] = mod_norm(v, p);
  }
  return out;
}

std::vector<float> dequantize(const std::vector<int64_t> &xq, int q_bits, int64_t p) {
  std::vector<float> out(xq.size());
  const double inv_scale = 1.0 / static_cast<double>(1LL << q_bits);
  const int64_t half = (p - 1) / 2;
  for (size_t i = 0; i < xq.size(); ++i) {
    int64_t v = mod_norm(xq[i], p);
    if (v > half) v -= p;
    out[i] = static_cast<float>(v * inv_scale);
  }
  return out;
}

MaskState encode_mask(int d, int num_clients, int target_active,
                      int privacy_guarantee, int64_t p, uint64_t seed) {
  if (!(0 < privacy_guarantee && privacy_guarantee < target_active &&
        target_active <= num_clients))
    throw std::invalid_argument("need 0 < T < U <= N");
  const int n_data = target_active - privacy_guarantee;
  const int d_pad = ((d + n_data - 1) / n_data) * n_data;
  const int chunk = d_pad / n_data;

  std::mt19937_64 rng(seed);
  std::uniform_int_distribution<int64_t> dist(0, p - 1);

  MaskState st;
  st.local_mask.resize(d_pad);
  for (auto &v : st.local_mask) v = dist(rng);

  std::vector<std::vector<int64_t>> payload(target_active, std::vector<int64_t>(chunk));
  for (int r = 0; r < n_data; ++r)
    for (int t = 0; t < chunk; ++t) payload[r][t] = st.local_mask[static_cast<size_t>(r) * chunk + t];
  for (int r = n_data; r < target_active; ++r)
    for (int t = 0; t < chunk; ++t) payload[r][t] = dist(rng);  // T-privacy noise rows

  // beta = 1..N (client points), alpha = N+1..N+U (payload points) — same
  // geometry as fedml_tpu/core/mpc/lightsecagg.py LightSecAggConfig.
  std::vector<int64_t> beta(num_clients), alpha(target_active);
  for (int i = 0; i < num_clients; ++i) beta[i] = i + 1;
  for (int i = 0; i < target_active; ++i) alpha[i] = num_clients + 1 + i;
  st.encoded_shares = lcc_encode(payload, beta, alpha, p);
  return st;
}

std::vector<int64_t> mask_vector(const std::vector<int64_t> &x_finite,
                                 const MaskState &state, int64_t p) {
  std::vector<int64_t> y(x_finite.size());
  for (size_t i = 0; i < x_finite.size(); ++i)
    y[i] = mod_norm(x_finite[i] + state.local_mask[i], p);
  return y;
}

std::vector<int64_t> aggregate_encoded_mask(
    const std::vector<std::vector<int64_t>> &received_shares, int64_t p) {
  if (received_shares.empty()) return {};
  std::vector<int64_t> agg(received_shares[0].size(), 0);
  for (const auto &share : received_shares)
    for (size_t t = 0; t < share.size(); ++t) agg[t] = mod_norm(agg[t] + share[t], p);
  return agg;
}

}  // namespace fedml_edge
