// CLI smoke binary (reference: MobileNN/src/main_MNN_train.cpp — "demo.out
// mnist <model> <data> ..."). Trains the dense engine on synthetic or file
// data and prints per-epoch loss/accuracy; exit 0 iff final accuracy clears
// a sanity bar, so this doubles as the native test.

#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "fedml_edge/client_manager.h"

int main(int argc, char **argv) {
  const char *dataset = argc > 1 ? argv[1] : "synthetic";
  const char *model_path = argc > 2 ? argv[2] : "";
  const char *data_path = argc > 3 ? argv[3] : "";
  int epochs = argc > 4 ? std::atoi(argv[4]) : 5;

  fedml_edge::FedMLClientManager manager;
  manager.init(model_path, data_path, dataset, /*train_size=*/512,
               /*test_size=*/128, /*batch_size=*/32, /*lr=*/0.1, epochs,
               nullptr,
               [](int epoch, float acc) { std::printf("epoch %d acc %.4f\n", epoch, acc); },
               [](int epoch, float loss) { std::printf("epoch %d loss %.4f\n", epoch, loss); });
  manager.train();
  auto *t = manager.trainer();
  float acc = t->evaluate(t->model(), t->data(), 0);
  std::printf("final accuracy: %.4f (%s)\n", acc, manager.get_epoch_and_loss().c_str());
  return acc > 0.6f ? 0 : 1;
}
