#include "fedml_edge/dense_trainer.h"

#include <algorithm>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <numeric>
#include <random>

namespace fedml_edge {

void FedMLBaseTrainer::init(const char *model_cache_path, const char *data_cache_path,
                            const char *dataset, int train_size, int test_size,
                            int batch_size, double learning_rate, int epoch_num,
                            ProgressCallback progress_cb, AccuracyCallback accuracy_cb,
                            LossCallback loss_cb) {
  model_cache_path_ = model_cache_path ? model_cache_path : "";
  data_cache_path_ = data_cache_path ? data_cache_path : "";
  dataset_ = dataset ? dataset : "";
  train_size_ = train_size;
  test_size_ = test_size;
  batch_size_ = batch_size > 0 ? batch_size : 32;
  learning_rate_ = learning_rate;
  epoch_num_ = epoch_num > 0 ? epoch_num : 1;
  progress_cb_ = std::move(progress_cb);
  accuracy_cb_ = std::move(accuracy_cb);
  loss_cb_ = std::move(loss_cb);
  cur_epoch_ = 0;
  cur_loss_ = 0.0f;
  stop_flag_ = false;
}

std::string FedMLBaseTrainer::get_epoch_and_loss() const {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%d,%.6f", cur_epoch_, cur_loss_);
  return buf;
}

bool FedMLBaseTrainer::stop_training() {
  stop_flag_ = true;
  return true;
}

bool DataSet::load(const std::string &path) {
  FILE *f = std::fopen(path.c_str(), "rb");
  if (!f) return false;
  int32_t hdr[3];
  if (std::fread(hdr, 4, 3, f) != 3 || hdr[0] <= 0 || hdr[1] <= 0 || hdr[2] <= 0) {
    std::fclose(f);
    return false;
  }
  n = hdr[0];
  dim = hdr[1];
  num_classes = hdr[2];
  x.assign(static_cast<size_t>(n) * dim, 0.0f);
  y.assign(n, 0);
  bool ok = std::fread(x.data(), sizeof(float), x.size(), f) == x.size() &&
            std::fread(y.data(), sizeof(int32_t), y.size(), f) == y.size();
  std::fclose(f);
  return ok;
}

DataSet DataSet::synthetic(int n, int dim, int num_classes, uint64_t seed) {
  // Deterministic linearly-separable-ish blobs: class centers on coordinate
  // axes + gaussian noise (mirrors the Python synthetic surrogate).
  DataSet d;
  d.n = n;
  d.dim = dim;
  d.num_classes = num_classes;
  d.x.resize(static_cast<size_t>(n) * dim);
  d.y.resize(n);
  std::mt19937_64 rng(seed);
  std::normal_distribution<float> noise(0.0f, 0.4f);
  for (int i = 0; i < n; ++i) {
    int c = static_cast<int>(rng() % static_cast<uint64_t>(num_classes));
    d.y[i] = c;
    for (int j = 0; j < dim; ++j) {
      float center = (j % num_classes == c) ? 1.5f : 0.0f;
      d.x[static_cast<size_t>(i) * dim + j] = center + noise(rng);
    }
  }
  return d;
}

void FedMLDenseTrainer::ensure_loaded() {
  if (loaded_) return;
  if (!model_.layers.empty()) {
    // architecture already configured / weights already installed
  } else if (!model_cache_path_.empty() && model_.load(model_cache_path_)) {
    // loaded serialized model from the server
  } else {
    model_ = DenseModel::create({60, 10}, 0);
  }
  if (data_cache_path_.empty() || !data_.load(data_cache_path_)) {
    int n = train_size_ > 0 ? train_size_ + std::max(test_size_, 0) : 512;
    data_ = DataSet::synthetic(n, model_.input_dim(), model_.output_dim(), 7);
  }
  if (train_size_ <= 0 || train_size_ > data_.n) train_size_ = data_.n;
  loaded_ = true;
}

namespace {

// Conv3x3 SAME + ReLU, then 2x2 maxpool (stride 2).
// conv_out: [in_h, in_w, out_c] post-ReLU; out: [h/2, w/2, out_c];
// argmax: per pooled cell, flat index into conv_out chosen by the max.
void conv_pool_forward(const DenseLayer &L, const float *in, std::vector<float> &conv_out,
                       std::vector<float> &out, std::vector<int32_t> *argmax) {
  const int H = L.in_h, W = L.in_w, IC = L.in_c, OC = L.out_c;
  conv_out.assign(static_cast<size_t>(H) * W * OC, 0.0f);
  for (int oy = 0; oy < H; ++oy) {
    for (int ox = 0; ox < W; ++ox) {
      for (int oc = 0; oc < OC; ++oc) {
        float s = L.b[oc];
        for (int ky = -1; ky <= 1; ++ky) {
          int iy = oy + ky;
          if (iy < 0 || iy >= H) continue;
          for (int kx = -1; kx <= 1; ++kx) {
            int ix = ox + kx;
            if (ix < 0 || ix >= W) continue;
            const float *in_px = in + (static_cast<size_t>(iy) * W + ix) * IC;
            const float *w_k = L.w.data() +
                ((static_cast<size_t>(ky + 1) * 3 + (kx + 1)) * IC) * OC + oc;
            for (int ic = 0; ic < IC; ++ic) s += in_px[ic] * w_k[static_cast<size_t>(ic) * OC];
          }
        }
        conv_out[(static_cast<size_t>(oy) * W + ox) * OC + oc] = std::max(s, 0.0f);
      }
    }
  }
  const int OH = H / 2, OW = W / 2;
  out.assign(static_cast<size_t>(OH) * OW * OC, 0.0f);
  if (argmax) argmax->assign(out.size(), 0);
  for (int py = 0; py < OH; ++py) {
    for (int px = 0; px < OW; ++px) {
      for (int oc = 0; oc < OC; ++oc) {
        float best = -1.0f;  // conv_out >= 0 post-ReLU
        int32_t best_idx = 0;
        for (int dy = 0; dy < 2; ++dy) {
          for (int dx = 0; dx < 2; ++dx) {
            int32_t idx = ((py * 2 + dy) * W + (px * 2 + dx)) * OC + oc;
            if (conv_out[idx] > best) {
              best = conv_out[idx];
              best_idx = idx;
            }
          }
        }
        size_t o = (static_cast<size_t>(py) * OW + px) * OC + oc;
        out[o] = best;
        if (argmax) (*argmax)[o] = best_idx;
      }
    }
  }
}

// Backward through pool + ReLU + conv. delta_out: [h/2, w/2, out_c];
// fills gw/gb (accumulated) and delta_in [in_h, in_w, in_c] (if non-null).
void conv_pool_backward(const DenseLayer &L, const float *in, const std::vector<float> &conv_out,
                        const std::vector<int32_t> &argmax, const std::vector<float> &delta_out,
                        std::vector<float> &gw, std::vector<float> &gb,
                        std::vector<float> *delta_in) {
  const int H = L.in_h, W = L.in_w, IC = L.in_c, OC = L.out_c;
  // unpool + ReLU mask -> delta at conv positions (sparse: one per pooled cell)
  if (delta_in) delta_in->assign(static_cast<size_t>(H) * W * IC, 0.0f);
  for (size_t o = 0; o < delta_out.size(); ++o) {
    float d = delta_out[o];
    if (d == 0.0f) continue;
    int32_t ci = argmax[o];
    if (conv_out[ci] <= 0.0f) continue;  // ReLU gate
    int oc = ci % OC;
    int pos = ci / OC;
    int ox = pos % W, oy = pos / W;
    gb[oc] += d;
    for (int ky = -1; ky <= 1; ++ky) {
      int iy = oy + ky;
      if (iy < 0 || iy >= H) continue;
      for (int kx = -1; kx <= 1; ++kx) {
        int ix = ox + kx;
        if (ix < 0 || ix >= W) continue;
        const float *in_px = in + (static_cast<size_t>(iy) * W + ix) * IC;
        size_t wbase = ((static_cast<size_t>(ky + 1) * 3 + (kx + 1)) * IC) * OC + oc;
        for (int ic = 0; ic < IC; ++ic) {
          gw[wbase + static_cast<size_t>(ic) * OC] += in_px[ic] * d;
          if (delta_in)
            (*delta_in)[(static_cast<size_t>(iy) * W + ix) * IC + ic] +=
                L.w[wbase + static_cast<size_t>(ic) * OC] * d;
        }
      }
    }
  }
}

void dense_forward_layer(const DenseLayer &L, const float *in, std::vector<float> &out, bool relu) {
  out.assign(L.out_dim, 0.0f);
  for (int o = 0; o < L.out_dim; ++o) {
    float s = L.b[o];
    for (int i = 0; i < L.in_dim; ++i)
      s += in[i] * L.w[static_cast<size_t>(i) * L.out_dim + o];
    out[o] = relu ? std::max(s, 0.0f) : s;
  }
}

}  // namespace

float FedMLDenseTrainer::train_epoch(DenseModel &model, const DataSet &data, int epoch) {
  const int n = std::min(train_size_ > 0 ? train_size_ : data.n, data.n);
  const int nl = static_cast<int>(model.layers.size());
  std::vector<int> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::mt19937_64 rng(static_cast<uint64_t>(epoch) * 0x9E37ULL + 13);
  std::shuffle(order.begin(), order.end(), rng);

  // per-layer buffers for one sample
  std::vector<std::vector<float>> acts(nl + 1), conv_outs(nl), deltas(nl);
  std::vector<std::vector<int32_t>> argmaxes(nl);
  double loss_sum = 0.0;
  int steps = 0;

  for (int start = 0; start < n && !stop_flag_; start += batch_size_) {
    int bsz = std::min(batch_size_, n - start);
    std::vector<std::vector<float>> gw(nl), gb(nl);
    for (int l = 0; l < nl; ++l) {
      gw[l].assign(model.layers[l].w.size(), 0.0f);
      gb[l].assign(model.layers[l].b.size(), 0.0f);
    }
    for (int bi = 0; bi < bsz; ++bi) {
      int i = order[start + bi];
      acts[0].assign(data.x.begin() + static_cast<size_t>(i) * data.dim,
                     data.x.begin() + static_cast<size_t>(i + 1) * data.dim);
      // forward
      for (int l = 0; l < nl; ++l) {
        const auto &L = model.layers[l];
        if (L.kind == kConv3x3Pool) {
          conv_pool_forward(L, acts[l].data(), conv_outs[l], acts[l + 1], &argmaxes[l]);
        } else {
          dense_forward_layer(L, acts[l].data(), acts[l + 1], l + 1 < nl);
        }
      }
      // softmax cross-entropy on the head
      auto &logits = acts[nl];
      float mx = *std::max_element(logits.begin(), logits.end());
      double denom = 0.0;
      for (float v : logits) denom += std::exp(v - mx);
      int label = data.y[i];
      loss_sum += -(logits[label] - mx - std::log(denom));
      deltas[nl - 1].assign(logits.size(), 0.0f);
      for (size_t o = 0; o < logits.size(); ++o) {
        float p = static_cast<float>(std::exp(logits[o] - mx) / denom);
        deltas[nl - 1][o] = p - (static_cast<int>(o) == label ? 1.0f : 0.0f);
      }
      // backward
      for (int l = nl - 1; l >= 0; --l) {
        const auto &L = model.layers[l];
        std::vector<float> *din = l > 0 ? &deltas[l - 1] : nullptr;
        if (L.kind == kConv3x3Pool) {
          conv_pool_backward(L, acts[l].data(), conv_outs[l], argmaxes[l], deltas[l],
                             gw[l], gb[l], din);
          // delta_in is pre-activation of the PREVIOUS layer's output; apply
          // the previous layer's ReLU gate below (dense case handles it)
          if (din && l > 0 && model.layers[l - 1].kind == kDense) {
            for (int in = 0; in < L.in_dim; ++in)
              if (acts[l][in] <= 0.0f) (*din)[in] = 0.0f;
          }
        } else {
          for (int o = 0; o < L.out_dim; ++o) {
            float d = deltas[l][o];
            gb[l][o] += d;
            for (int in = 0; in < L.in_dim; ++in)
              gw[l][static_cast<size_t>(in) * L.out_dim + o] += acts[l][in] * d;
          }
          if (l > 0) {
            deltas[l - 1].assign(L.in_dim, 0.0f);
            for (int in = 0; in < L.in_dim; ++in) {
              float s = 0.0f;
              for (int o = 0; o < L.out_dim; ++o)
                s += L.w[static_cast<size_t>(in) * L.out_dim + o] * deltas[l][o];
              // gate by the previous layer's ReLU (dense hidden) — conv
              // outputs are post-pool-of-ReLU, their gate lives inside
              // conv_pool_backward of that layer
              deltas[l - 1][in] =
                  (model.layers[l - 1].kind == kDense && acts[l][in] <= 0.0f) ? 0.0f : s;
            }
          }
        }
      }
      ++steps;
    }
    float lr = static_cast<float>(learning_rate_) / static_cast<float>(bsz);
    for (int l = 0; l < nl; ++l) {
      auto &L = model.layers[l];
      for (size_t k = 0; k < L.w.size(); ++k) L.w[k] -= lr * gw[l][k];
      for (size_t k = 0; k < L.b.size(); ++k) L.b[k] -= lr * gb[l][k];
    }
    if (progress_cb_) progress_cb_(100.0f * (start + bsz) / static_cast<float>(n));
  }
  return steps > 0 ? static_cast<float>(loss_sum / steps) : 0.0f;
}

float FedMLDenseTrainer::evaluate(const DenseModel &model, const DataSet &data, int limit) const {
  int n = std::min(limit > 0 ? limit : data.n, data.n);
  if (n == 0) return 0.0f;
  int correct = 0;
  const int nl = static_cast<int>(model.layers.size());
  std::vector<float> cur, next, conv_scratch;
  for (int i = 0; i < n; ++i) {
    cur.assign(data.x.begin() + static_cast<size_t>(i) * data.dim,
               data.x.begin() + static_cast<size_t>(i + 1) * data.dim);
    for (int l = 0; l < nl; ++l) {
      const auto &L = model.layers[l];
      if (L.kind == kConv3x3Pool) {
        conv_pool_forward(L, cur.data(), conv_scratch, next, nullptr);
      } else {
        dense_forward_layer(L, cur.data(), next, l + 1 < nl);
      }
      cur.swap(next);
    }
    int pred = static_cast<int>(std::max_element(cur.begin(), cur.end()) - cur.begin());
    if (pred == data.y[i]) ++correct;
  }
  return static_cast<float>(correct) / static_cast<float>(n);
}

std::string FedMLDenseTrainer::train() {
  ensure_loaded();
  for (int e = 0; e < epoch_num_ && !stop_flag_; ++e) {
    cur_loss_ = train_epoch(model_, data_, e);
    cur_epoch_ = e;
    if (loss_cb_) loss_cb_(e, cur_loss_);
    if (accuracy_cb_) accuracy_cb_(e, evaluate(model_, data_, train_size_));
  }
  if (!model_cache_path_.empty()) model_.save(model_cache_path_);
  return model_cache_path_;
}

}  // namespace fedml_edge
