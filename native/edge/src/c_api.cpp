// C ABI for the edge engine.
//
// The reference bridges MobileNN to the app layer via JNI
// (android/fedmlsdk/src/main/jni/JniFedMLClientManager.cpp); here the host
// is Python, so the bridge is a plain C ABI consumed with ctypes
// (fedml_tpu/cross_device/native_bridge.py). Memory contract: the library
// owns every buffer it returns; buffers stay valid until the next call on
// the same handle or edge_destroy.

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "fedml_edge/client_manager.h"
#include "fedml_edge/light_secagg.h"

using fedml_edge::FedMLClientManager;

namespace {
struct EdgeHandle {
  FedMLClientManager manager;
  std::string last_string;
  std::vector<float> float_buf;
  std::vector<int64_t> mask_buf;
  fedml_edge::MaskState mask_state;
};
}  // namespace

extern "C" {

void *edge_create() { return new EdgeHandle(); }

void edge_destroy(void *h) { delete static_cast<EdgeHandle *>(h); }

void edge_init(void *h, const char *model_path, const char *data_path,
               const char *dataset, int train_size, int test_size,
               int batch_size, double lr, int epochs) {
  static_cast<EdgeHandle *>(h)->manager.init(model_path, data_path, dataset,
                                             train_size, test_size, batch_size,
                                             lr, epochs);
}

const char *edge_train(void *h) {
  auto *e = static_cast<EdgeHandle *>(h);
  e->last_string = e->manager.train();
  return e->last_string.c_str();
}

const char *edge_get_epoch_and_loss(void *h) {
  auto *e = static_cast<EdgeHandle *>(h);
  e->last_string = e->manager.get_epoch_and_loss();
  return e->last_string.c_str();
}

int edge_stop_training(void *h) {
  return static_cast<EdgeHandle *>(h)->manager.stop_training() ? 1 : 0;
}

double edge_evaluate(void *h, int limit) {
  auto *e = static_cast<EdgeHandle *>(h);
  auto *t = e->manager.trainer();
  return t->evaluate(t->model(), t->data(), limit);
}

// --- model blob access ------------------------------------------------------

// Define the model architecture up front (layer dims, e.g. [60, 10] for LR)
// so the host can push weights before the first train() call.
int edge_configure_model(void *h, const int32_t *dims, int ndims, uint64_t seed) {
  if (ndims < 2) return -1;
  std::vector<int> d(dims, dims + ndims);
  static_cast<EdgeHandle *>(h)->manager.trainer()->model() =
      fedml_edge::DenseModel::create(d, seed);
  return 0;
}


// LeNet-style conv model: conv3x3+ReLU+maxpool2 stages over (in_h, in_w,
// in_c), then dense layers (reference mobile engine trains LeNet-class
// conv graphs, MobileNN/src/train/FedMLMNNTrainer.cpp).
int edge_configure_conv_model(void *h, int in_h, int in_w, int in_c,
                              const int32_t *conv_channels, int n_conv,
                              const int32_t *dense_dims, int n_dense, uint64_t seed) {
  if (in_h <= 0 || in_w <= 0 || in_c <= 0 || n_conv < 1 || n_dense < 1) return -1;
  std::vector<int> cc(conv_channels, conv_channels + n_conv);
  std::vector<int> dd(dense_dims, dense_dims + n_dense);
  auto model = fedml_edge::DenseModel::create_conv(in_h, in_w, in_c, cc, dd, seed);
  if (model.layers.empty()) return -1;  // invalid spec (e.g. odd spatial dim)
  static_cast<EdgeHandle *>(h)->manager.trainer()->model() = std::move(model);
  return 0;
}

int64_t edge_num_params(void *h) {
  return static_cast<int64_t>(
      static_cast<EdgeHandle *>(h)->manager.trainer()->model().num_params());
}

// Copies the flat float32 params into out (caller allocates n floats).
int edge_get_model(void *h, float *out, int64_t n) {
  auto flat = static_cast<EdgeHandle *>(h)->manager.trainer()->model().flatten();
  if (static_cast<int64_t>(flat.size()) != n) return -1;
  std::memcpy(out, flat.data(), sizeof(float) * flat.size());
  return 0;
}

int edge_set_model(void *h, const float *in, int64_t n) {
  auto &model = static_cast<EdgeHandle *>(h)->manager.trainer()->model();
  if (static_cast<int64_t>(model.num_params()) != n) return -1;
  std::vector<float> flat(in, in + n);
  model.unflatten(flat);
  return 0;
}

// --- LightSecAgg ------------------------------------------------------------

// Offline phase: draw + encode this client's mask. Returns chunk length
// (elements per peer share) or -1.
int64_t edge_lsa_encode_mask(void *h, int num_clients, int target_active,
                             int privacy_guarantee, int64_t prime, uint64_t seed) {
  auto *e = static_cast<EdgeHandle *>(h);
  int d = static_cast<int>(e->manager.trainer()->model().num_params());
  try {
    e->mask_state = fedml_edge::encode_mask(d, num_clients, target_active,
                                            privacy_guarantee, prime, seed);
  } catch (...) {
    return -1;
  }
  return e->mask_state.encoded_shares.empty()
             ? 0
             : static_cast<int64_t>(e->mask_state.encoded_shares[0].size());
}

// Copy the encoded share destined for peer j (chunk int64s).
int edge_lsa_get_share(void *h, int peer, int64_t *out, int64_t chunk) {
  auto *e = static_cast<EdgeHandle *>(h);
  const auto &shares = e->mask_state.encoded_shares;
  if (peer < 0 || peer >= static_cast<int>(shares.size())) return -1;
  if (static_cast<int64_t>(shares[peer].size()) != chunk) return -1;
  std::memcpy(out, shares[peer].data(), sizeof(int64_t) * chunk);
  return 0;
}

// Online phase: quantize the current model and add the mask; writes d int64s.
int edge_lsa_masked_model(void *h, int q_bits, int64_t prime, int64_t *out, int64_t d) {
  auto *e = static_cast<EdgeHandle *>(h);
  auto flat = e->manager.trainer()->model().flatten();
  if (static_cast<int64_t>(flat.size()) != d) return -1;
  auto xq = fedml_edge::quantize(flat, q_bits, prime);
  auto y = fedml_edge::mask_vector(xq, e->mask_state, prime);
  std::memcpy(out, y.data(), sizeof(int64_t) * d);
  return 0;
}

// Aggregate the active peers' shares: in = n_active concatenated chunks.
int edge_lsa_aggregate_shares(void *h, const int64_t *in, int n_active,
                              int64_t chunk, int64_t prime, int64_t *out) {
  std::vector<std::vector<int64_t>> received(n_active, std::vector<int64_t>(chunk));
  for (int i = 0; i < n_active; ++i)
    std::memcpy(received[i].data(), in + static_cast<int64_t>(i) * chunk,
                sizeof(int64_t) * chunk);
  auto agg = fedml_edge::aggregate_encoded_mask(received, prime);
  std::memcpy(out, agg.data(), sizeof(int64_t) * chunk);
  return 0;
}

}  // extern "C"
