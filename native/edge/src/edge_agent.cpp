// Native always-on edge FL client over the cross-process message plane.
//
// Reference: the Android client (android/fedmlsdk) is a real NETWORK
// participant — it subscribes MQTT topics, downloads the model file, trains
// with the native engine and uploads the result. This binary is that
// participant for this framework: it speaks the socket-broker protocol
// (core/distributed/communication/mqtt_s3/socket_broker.py — JSON lines,
// base64 payloads), consumes the shared blob format (dense_model.h), and
// runs the cross-device WAN round (cross_device/wan.py topic scheme:
//   server->edge  fedml_<run>_<server>_<edge>   {type:init|sync|finish,
//                                                round, model_url}
//   edge->server  fedml_<run>_<edge>            {type:model_upload, ...}
// ), so a federation can mix python edges and this native edge freely
// (tests/test_native_edge_agent.py proves exactly that).
//
// Usage:
//   edge_agent <broker_host> <broker_port> <run_id> <edge_id> <server_id>
//              <store_dir> [data=synthetic|/path/to/data.bin] [train_size=256]
//              [batch=32] [lr=0.1] [epochs=1] [sample_num=256]
//
// "data": the literal string "synthetic" trains on the deterministic
// surrogate; any other value is a dataset blob path (codec.py
// dataset_to_bytes format) loaded by the native trainer.

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>

#include "fedml_edge/client_manager.h"
#include "fedml_edge/dense_model.h"
#include "fedml_edge/light_secagg.h"

namespace {

// --- minimal base64 (the broker frames payloads with it) --------------------

const char kB64[] = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

std::string b64_encode(const std::string &in) {
  std::string out;
  out.reserve((in.size() + 2) / 3 * 4);
  size_t i = 0;
  while (i + 2 < in.size()) {
    uint32_t v = (uint8_t(in[i]) << 16) | (uint8_t(in[i + 1]) << 8) | uint8_t(in[i + 2]);
    out += kB64[(v >> 18) & 63];
    out += kB64[(v >> 12) & 63];
    out += kB64[(v >> 6) & 63];
    out += kB64[v & 63];
    i += 3;
  }
  if (i + 1 == in.size()) {
    uint32_t v = uint8_t(in[i]) << 16;
    out += kB64[(v >> 18) & 63];
    out += kB64[(v >> 12) & 63];
    out += "==";
  } else if (i + 2 == in.size()) {
    uint32_t v = (uint8_t(in[i]) << 16) | (uint8_t(in[i + 1]) << 8);
    out += kB64[(v >> 18) & 63];
    out += kB64[(v >> 12) & 63];
    out += kB64[(v >> 6) & 63];
    out += '=';
  }
  return out;
}

int b64_val(char c) {
  if (c >= 'A' && c <= 'Z') return c - 'A';
  if (c >= 'a' && c <= 'z') return c - 'a' + 26;
  if (c >= '0' && c <= '9') return c - '0' + 52;
  if (c == '+') return 62;
  if (c == '/') return 63;
  return -1;
}

std::string b64_decode(const std::string &in) {
  std::string out;
  uint32_t buf = 0;
  int bits = 0;
  for (char c : in) {
    int v = b64_val(c);
    if (v < 0) continue;  // '=', whitespace
    buf = (buf << 6) | uint32_t(v);
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out += char((buf >> bits) & 0xFF);
    }
  }
  return out;
}

// --- minimal JSON field extraction (controlled, framework-generated docs) ---

bool json_find_key(const std::string &doc, const std::string &key, size_t *pos) {
  std::string needle = "\"" + key + "\"";
  size_t p = doc.find(needle);
  if (p == std::string::npos) return false;
  p = doc.find(':', p + needle.size());
  if (p == std::string::npos) return false;
  ++p;
  while (p < doc.size() && (doc[p] == ' ' || doc[p] == '\t')) ++p;
  *pos = p;
  return true;
}

bool json_string(const std::string &doc, const std::string &key, std::string *out) {
  size_t p;
  if (!json_find_key(doc, key, &p) || p >= doc.size() || doc[p] != '"') return false;
  size_t e = p + 1;
  std::string s;
  while (e < doc.size() && doc[e] != '"') {
    if (doc[e] == '\\' && e + 1 < doc.size()) ++e;  // framework urls: rare
    s += doc[e++];
  }
  *out = s;
  return true;
}

bool json_int(const std::string &doc, const std::string &key, long *out) {
  size_t p;
  if (!json_find_key(doc, key, &p)) return false;
  *out = std::strtol(doc.c_str() + p, nullptr, 10);
  return true;
}

bool json_double(const std::string &doc, const std::string &key, double *out) {
  size_t p;
  if (!json_find_key(doc, key, &p)) return false;
  *out = std::strtod(doc.c_str() + p, nullptr);
  return true;
}

bool json_bool(const std::string &doc, const std::string &key, bool *out) {
  size_t p;
  if (!json_find_key(doc, key, &p) || p >= doc.size()) return false;
  if (doc.compare(p, 4, "true") == 0) { *out = true; return true; }
  if (doc.compare(p, 5, "false") == 0) { *out = false; return true; }
  return false;
}

std::string json_escape(const std::string &s) {
  std::string o;
  for (char c : s) {
    if (c == '"' || c == '\\') o += '\\';
    o += c;
  }
  return o;
}

// --- broker client ----------------------------------------------------------

class BrokerClient {
 public:
  bool connect_to(const std::string &host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) return false;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(uint16_t(port));
    hostent *he = gethostbyname(host.c_str());
    if (he == nullptr) return false;
    std::memcpy(&addr.sin_addr, he->h_addr, size_t(he->h_length));
    return ::connect(fd_, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) == 0;
  }

  bool send_line(const std::string &line) {
    std::string framed = line + "\n";
    const char *p = framed.data();
    size_t left = framed.size();
    while (left > 0) {
      // MSG_NOSIGNAL: a dead broker must surface as send()==-1 (our error
      // path), not SIGPIPE process death
      ssize_t n = ::send(fd_, p, left, MSG_NOSIGNAL);
      if (n <= 0) return false;
      p += n;
      left -= size_t(n);
    }
    return true;
  }

  // Blocking read of the next newline-terminated line.
  bool read_line(std::string *line) {
    while (true) {
      size_t nl = buf_.find('\n');
      if (nl != std::string::npos) {
        *line = buf_.substr(0, nl);
        buf_.erase(0, nl + 1);
        return true;
      }
      char chunk[4096];
      ssize_t n = ::recv(fd_, chunk, sizeof(chunk), 0);
      if (n <= 0) return false;
      buf_.append(chunk, size_t(n));
    }
  }

  bool subscribe(const std::string &topic) {
    return send_line("{\"op\": \"sub\", \"topic\": \"" + json_escape(topic) + "\"}");
  }

  bool publish(const std::string &topic, const std::string &payload) {
    return send_line("{\"op\": \"pub\", \"topic\": \"" + json_escape(topic) +
                     "\", \"payload\": \"" + b64_encode(payload) + "\"}");
  }

  ~BrokerClient() {
    if (fd_ >= 0) ::close(fd_);
  }

 private:
  int fd_ = -1;
  std::string buf_;
};

std::string strip_file_url(const std::string &url) {
  const std::string scheme = "file://";
  return url.rfind(scheme, 0) == 0 ? url.substr(scheme.size()) : url;
}

// --- int64 blob IO (little-endian; matches numpy '<i8' tobytes) -------------

bool write_i64(const std::string &path, const std::vector<int64_t> &flat) {
  FILE *f = std::fopen(path.c_str(), "wb");
  if (f == nullptr) return false;
  size_t n = std::fwrite(flat.data(), sizeof(int64_t), flat.size(), f);
  std::fclose(f);
  return n == flat.size();
}

bool read_i64(const std::string &path, std::vector<int64_t> *out) {
  FILE *f = std::fopen(path.c_str(), "rb");
  if (f == nullptr) return false;
  std::fseek(f, 0, SEEK_END);
  long bytes = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out->assign(size_t(bytes) / sizeof(int64_t), 0);
  size_t n = std::fread(out->data(), sizeof(int64_t), out->size(), f);
  std::fclose(f);
  return n == out->size();
}

bool json_int_array(const std::string &doc, const std::string &key,
                    std::vector<long> *out) {
  size_t p;
  if (!json_find_key(doc, key, &p) || p >= doc.size() || doc[p] != '[') return false;
  ++p;
  out->clear();
  while (p < doc.size() && doc[p] != ']') {
    char *end = nullptr;
    long v = std::strtol(doc.c_str() + p, &end, 10);
    if (end == doc.c_str() + p) {
      ++p;
      continue;
    }
    out->push_back(v);
    p = size_t(end - doc.c_str());
  }
  return true;
}

}  // namespace

int main(int argc, char **argv) {
  if (argc < 7) {
    std::fprintf(stderr,
                 "usage: edge_agent <host> <port> <run_id> <edge_id> <server_id>"
                 " <store_dir> [dataset] [train_size] [batch] [lr] [epochs] [sample_num]\n");
    return 2;
  }
  const std::string host = argv[1];
  const int port = std::atoi(argv[2]);
  const std::string run_id = argv[3];
  const int edge_id = std::atoi(argv[4]);
  const int server_id = std::atoi(argv[5]);
  const std::string store_dir = argv[6];
  const std::string data_spec = argc > 7 ? argv[7] : "synthetic";
  // non-"synthetic" means a dataset blob path — wire it where the trainer
  // actually looks (data_cache_path); a bare dataset NAME would silently
  // fall back to synthetic
  const std::string data_path = data_spec == "synthetic" ? "" : data_spec;
  const int train_size = argc > 8 ? std::atoi(argv[8]) : 256;
  const int batch = argc > 9 ? std::atoi(argv[9]) : 32;
  const double lr = argc > 10 ? std::atof(argv[10]) : 0.1;
  const int epochs = argc > 11 ? std::atoi(argv[11]) : 1;
  const int sample_num = argc > 12 ? std::atoi(argv[12]) : train_size;

  fedml_edge::FedMLClientManager manager;
  manager.init("", data_path.c_str(), "synthetic", train_size, /*test_size=*/64,
               batch, lr, epochs);

  BrokerClient broker;
  if (!broker.connect_to(host, port)) {
    std::fprintf(stderr, "edge_agent: cannot reach broker %s:%d\n", host.c_str(), port);
    return 1;
  }
  const std::string s2c = "fedml_" + run_id + "_" + std::to_string(server_id) +
                          "_" + std::to_string(edge_id);
  const std::string c2s = "fedml_" + run_id + "_" + std::to_string(edge_id);
  if (!broker.subscribe(s2c)) return 1;
  std::printf("edge_agent %d online (run %s, broker %s:%d)\n", edge_id,
              run_id.c_str(), host.c_str(), port);
  std::fflush(stdout);

  // LightSecAgg per-round state (secure mode: the sync message carries an
  // "lsa" config; protocol in cross_device/lsa_wan.py — this agent never
  // uploads a plaintext model in that mode)
  fedml_edge::MaskState mask_state;
  std::vector<int64_t> received_flat;  // N*chunk relayed shares, sender-major
  long received_round = -1;            // which round received_flat belongs to
  long lsa_N = 0, lsa_prime = 0, lsa_qbits = 16;

  std::string line;
  while (broker.read_line(&line)) {
    std::string op;
    if (!json_string(line, "op", &op) || op != "msg") continue;
    std::string payload_b64;
    if (!json_string(line, "payload", &payload_b64)) continue;
    const std::string doc = b64_decode(payload_b64);

    std::string type;
    if (!json_string(doc, "type", &type)) continue;
    if (type == "finish") {
      std::printf("edge_agent %d: finish\n", edge_id);
      return 0;
    }
    long round = 0;
    json_int(doc, "round", &round);

    if (type == "lsa_shares_dist") {
      // server relayed every sender's share addressed to us: keep rows
      std::string url;
      std::vector<int64_t> flat;
      if (!json_string(doc, "shares_url", &url) ||
          !read_i64(strip_file_url(url), &flat) || lsa_N <= 0) {
        std::fprintf(stderr, "edge_agent %d: bad shares dist (round %ld)\n",
                     edge_id, round);
        continue;
      }
      received_flat = flat;
      received_round = round;
      continue;
    }

    if (type == "lsa_active") {
      std::vector<long> active;
      if (!json_int_array(doc, "active", &active) || lsa_N <= 0) continue;
      if (received_flat.empty() || received_round != round) {
        // answering with another round's shares would silently corrupt the
        // server's reconstructed aggregate — refuse loudly instead
        std::fprintf(stderr, "edge_agent %d: no shares for round %ld (have %ld)\n",
                     edge_id, round, received_round);
        continue;
      }
      size_t chunk = received_flat.size() / size_t(lsa_N);
      bool bad_index = false;
      for (long a : active) {
        if (a < 0 || a >= lsa_N) bad_index = true;  // untrusted input: an
        // out-of-range cohort index would read past received_flat
      }
      if (bad_index) {
        std::fprintf(stderr, "edge_agent %d: active set out of range (N=%ld)\n",
                     edge_id, lsa_N);
        continue;
      }
      std::vector<std::vector<int64_t>> rows;
      for (long a : active) {
        auto begin = received_flat.begin() + long(chunk) * a;
        rows.emplace_back(begin, begin + long(chunk));
      }
      auto agg = fedml_edge::aggregate_encoded_mask(rows, lsa_prime);
      const std::string path = store_dir + "/lsa_aggshare_native_" +
                               std::to_string(edge_id) + "_r" + std::to_string(round) + ".bin";
      if (!write_i64(path, agg)) continue;
      const std::string msg =
          "{\"type\": \"lsa_agg_share\", \"round\": " + std::to_string(round) +
          ", \"edge_id\": " + std::to_string(edge_id) +
          ", \"share_url\": \"file://" + json_escape(path) + "\"}";
      if (!broker.publish(c2s, msg)) return 1;
      continue;
    }

    if (type != "init" && type != "sync") continue;
    std::string url;
    if (!json_string(doc, "model_url", &url)) continue;

    auto &model = manager.trainer()->model();
    if (!model.load(strip_file_url(url))) {
      std::fprintf(stderr, "edge_agent %d: bad model blob %s\n", edge_id, url.c_str());
      continue;
    }
    manager.train();

    long N = 0;
    if (json_int(doc, "N", &N) && N > 0) {
      // SECURE round: shares out, masked model out, plaintext stays here
      long U = N, T = 1;
      lsa_prime = fedml_edge::kDefaultPrime;
      json_int(doc, "U", &U);
      json_int(doc, "T", &T);
      json_int(doc, "prime", &lsa_prime);
      json_int(doc, "q_bits", &lsa_qbits);
      lsa_N = N;
      received_flat.clear();  // round-scoped: stale shares must never be
      received_round = -1;    // aggregated for a later round
      auto flat = model.flatten();
      bool weighted = false;
      json_bool(doc, "weighted", &weighted);
      if (weighted) {
        // normalized sample weight rides as one extra masked element:
        // the server recovers sum(w*x) and sum(w), never this w.
        // strtod, not strtol: the python side sends a FLOAT scale
        double ws = 1024.0;
        json_double(doc, "weight_scale", &ws);
        const float w_norm = float(double(sample_num) / ws);
        for (auto &v : flat) v *= w_norm;
        flat.push_back(w_norm);
      }
      // CSPRNG seed: a seed computable from public values (edge id, round)
      // would let the server regenerate the mask and unmask this edge's
      // individual model — the exact thing LightSecAgg exists to prevent
      std::random_device rd;
      const uint64_t seed = (uint64_t(rd()) << 32) ^ uint64_t(rd());
      mask_state = fedml_edge::encode_mask(
          int(flat.size()), int(N), int(U), int(T), lsa_prime, seed);

      std::vector<int64_t> shares_flat;
      for (const auto &row : mask_state.encoded_shares)
        shares_flat.insert(shares_flat.end(), row.begin(), row.end());
      const std::string sp = store_dir + "/lsa_shares_native_" +
                             std::to_string(edge_id) + "_r" + std::to_string(round) + ".bin";
      if (!write_i64(sp, shares_flat)) continue;
      std::string msg = "{\"type\": \"lsa_shares\", \"round\": " + std::to_string(round) +
                        ", \"edge_id\": " + std::to_string(edge_id) +
                        ", \"shares_url\": \"file://" + json_escape(sp) + "\"}";
      if (!broker.publish(c2s, msg)) return 1;

      auto y = fedml_edge::mask_vector(
          fedml_edge::quantize(flat, int(lsa_qbits), lsa_prime), mask_state, lsa_prime);
      const std::string yp = store_dir + "/lsa_masked_native_" +
                             std::to_string(edge_id) + "_r" + std::to_string(round) + ".bin";
      if (!write_i64(yp, y)) continue;
      msg = "{\"type\": \"lsa_masked_model\", \"round\": " + std::to_string(round) +
            ", \"edge_id\": " + std::to_string(edge_id) +
            ", \"model_url\": \"file://" + json_escape(yp) + "\"}";
      if (!broker.publish(c2s, msg)) return 1;
      std::printf("edge_agent %d: round %ld trained + MASKED upload\n", edge_id, round);
      std::fflush(stdout);
      continue;
    }

    const std::string out_path = store_dir + "/edge_" + std::to_string(edge_id) +
                                 "_round_" + std::to_string(round) + "_native.bin";
    if (!model.save(out_path)) {
      std::fprintf(stderr, "edge_agent %d: cannot write %s\n", edge_id, out_path.c_str());
      continue;
    }
    const std::string upload =
        "{\"type\": \"model_upload\", \"edge_id\": " + std::to_string(edge_id) +
        ", \"round\": " + std::to_string(round) +
        ", \"model_url\": \"file://" + json_escape(out_path) +
        "\", \"sample_num\": " + std::to_string(sample_num) + "}";
    if (!broker.publish(c2s, upload)) return 1;
    std::printf("edge_agent %d: round %ld trained + uploaded\n", edge_id, round);
    std::fflush(stdout);
  }
  // read loop ended WITHOUT a finish message: the broker connection dropped.
  // Exit nonzero so a supervisor restarts this participant rather than
  // mistaking it for a clean shutdown.
  std::fprintf(stderr, "edge_agent %d: broker connection lost\n", edge_id);
  return 3;
}
