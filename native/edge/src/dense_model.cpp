#include "fedml_edge/dense_model.h"

#include <cmath>
#include <cstdio>
#include <cstring>

namespace fedml_edge {

namespace {
// splitmix64: tiny deterministic PRNG for init + synthetic data.
uint64_t splitmix64(uint64_t &state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

float uniform01(uint64_t &state) {
  return (splitmix64(state) >> 11) * (1.0f / 9007199254740992.0f);
}
}  // namespace

size_t DenseModel::num_params() const {
  size_t n = 0;
  for (const auto &l : layers) n += l.w.size() + l.b.size();
  return n;
}

std::vector<float> DenseModel::flatten() const {
  std::vector<float> flat;
  flat.reserve(num_params());
  for (const auto &l : layers) {
    flat.insert(flat.end(), l.w.begin(), l.w.end());
    flat.insert(flat.end(), l.b.begin(), l.b.end());
  }
  return flat;
}

void DenseModel::unflatten(const std::vector<float> &flat) {
  size_t off = 0;
  for (auto &l : layers) {
    std::memcpy(l.w.data(), flat.data() + off, l.w.size() * sizeof(float));
    off += l.w.size();
    std::memcpy(l.b.data(), flat.data() + off, l.b.size() * sizeof(float));
    off += l.b.size();
  }
}

bool DenseModel::save(const std::string &path) const {
  FILE *f = std::fopen(path.c_str(), "wb");
  if (!f) return false;
  int32_t magic = kModelMagic, n = static_cast<int32_t>(layers.size());
  std::fwrite(&magic, 4, 1, f);
  std::fwrite(&n, 4, 1, f);
  for (const auto &l : layers) {
    std::fwrite(&l.in_dim, 4, 1, f);
    std::fwrite(&l.out_dim, 4, 1, f);
  }
  for (const auto &l : layers) {
    std::fwrite(l.w.data(), sizeof(float), l.w.size(), f);
    std::fwrite(l.b.data(), sizeof(float), l.b.size(), f);
  }
  std::fclose(f);
  return true;
}

bool DenseModel::load(const std::string &path) {
  FILE *f = std::fopen(path.c_str(), "rb");
  if (!f) return false;
  int32_t magic = 0, n = 0;
  if (std::fread(&magic, 4, 1, f) != 1 || magic != kModelMagic ||
      std::fread(&n, 4, 1, f) != 1 || n <= 0 || n > 64) {
    std::fclose(f);
    return false;
  }
  layers.assign(n, DenseLayer{});
  for (auto &l : layers) {
    if (std::fread(&l.in_dim, 4, 1, f) != 1 || std::fread(&l.out_dim, 4, 1, f) != 1 ||
        l.in_dim <= 0 || l.out_dim <= 0) {
      std::fclose(f);
      return false;
    }
  }
  for (auto &l : layers) {
    l.w.assign(static_cast<size_t>(l.in_dim) * l.out_dim, 0.0f);
    l.b.assign(l.out_dim, 0.0f);
    if (std::fread(l.w.data(), sizeof(float), l.w.size(), f) != l.w.size() ||
        std::fread(l.b.data(), sizeof(float), l.b.size(), f) != l.b.size()) {
      std::fclose(f);
      return false;
    }
  }
  std::fclose(f);
  return true;
}

DenseModel DenseModel::create(const std::vector<int> &dims, uint64_t seed) {
  DenseModel m;
  uint64_t state = seed * 0x9E3779B97F4A7C15ULL + 1;
  for (size_t i = 0; i + 1 < dims.size(); ++i) {
    DenseLayer l;
    l.in_dim = dims[i];
    l.out_dim = dims[i + 1];
    l.w.resize(static_cast<size_t>(l.in_dim) * l.out_dim);
    l.b.assign(l.out_dim, 0.0f);
    float scale = std::sqrt(2.0f / static_cast<float>(l.in_dim));
    for (auto &w : l.w) w = (uniform01(state) * 2.0f - 1.0f) * scale;
    m.layers.push_back(std::move(l));
  }
  return m;
}

}  // namespace fedml_edge
