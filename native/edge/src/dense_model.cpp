#include "fedml_edge/dense_model.h"

#include <cmath>
#include <cstdio>
#include <cstring>

namespace fedml_edge {

namespace {
// splitmix64: tiny deterministic PRNG for init + synthetic data.
uint64_t splitmix64(uint64_t &state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

float uniform01(uint64_t &state) {
  return (splitmix64(state) >> 11) * (1.0f / 9007199254740992.0f);
}

void init_weights(DenseLayer &l, uint64_t &state, int fan_in) {
  float scale = std::sqrt(2.0f / static_cast<float>(fan_in));
  for (auto &w : l.w) w = (uniform01(state) * 2.0f - 1.0f) * scale;
}
}  // namespace

size_t DenseModel::num_params() const {
  size_t n = 0;
  for (const auto &l : layers) n += l.w.size() + l.b.size();
  return n;
}

bool DenseModel::has_conv() const {
  for (const auto &l : layers)
    if (l.kind == kConv3x3Pool) return true;
  return false;
}

std::vector<float> DenseModel::flatten() const {
  std::vector<float> flat;
  flat.reserve(num_params());
  for (const auto &l : layers) {
    flat.insert(flat.end(), l.w.begin(), l.w.end());
    flat.insert(flat.end(), l.b.begin(), l.b.end());
  }
  return flat;
}

void DenseModel::unflatten(const std::vector<float> &flat) {
  size_t off = 0;
  for (auto &l : layers) {
    std::memcpy(l.w.data(), flat.data() + off, l.w.size() * sizeof(float));
    off += l.w.size();
    std::memcpy(l.b.data(), flat.data() + off, l.b.size() * sizeof(float));
    off += l.b.size();
  }
}

bool DenseModel::save(const std::string &path) const {
  FILE *f = std::fopen(path.c_str(), "wb");
  if (!f) return false;
  // dense-only models keep the v1 format so older peers stay compatible
  if (!has_conv()) {
    int32_t magic = kModelMagic, n = static_cast<int32_t>(layers.size());
    std::fwrite(&magic, 4, 1, f);
    std::fwrite(&n, 4, 1, f);
    for (const auto &l : layers) {
      std::fwrite(&l.in_dim, 4, 1, f);
      std::fwrite(&l.out_dim, 4, 1, f);
    }
  } else {
    int32_t magic = kModelMagicV2, n = static_cast<int32_t>(layers.size());
    std::fwrite(&magic, 4, 1, f);
    std::fwrite(&n, 4, 1, f);
    for (const auto &l : layers) {
      int32_t hdr[7] = {l.kind, l.in_dim, l.out_dim, l.in_h, l.in_w, l.in_c, l.out_c};
      std::fwrite(hdr, 4, 7, f);
    }
  }
  for (const auto &l : layers) {
    std::fwrite(l.w.data(), sizeof(float), l.w.size(), f);
    std::fwrite(l.b.data(), sizeof(float), l.b.size(), f);
  }
  std::fclose(f);
  return true;
}

bool DenseModel::load(const std::string &path) {
  FILE *f = std::fopen(path.c_str(), "rb");
  if (!f) return false;
  int32_t magic = 0, n = 0;
  if (std::fread(&magic, 4, 1, f) != 1 ||
      (magic != kModelMagic && magic != kModelMagicV2) ||
      std::fread(&n, 4, 1, f) != 1 || n <= 0 || n > 64) {
    std::fclose(f);
    return false;
  }
  layers.assign(n, DenseLayer{});
  for (auto &l : layers) {
    if (magic == kModelMagic) {
      if (std::fread(&l.in_dim, 4, 1, f) != 1 || std::fread(&l.out_dim, 4, 1, f) != 1 ||
          l.in_dim <= 0 || l.out_dim <= 0) {
        std::fclose(f);
        return false;
      }
      l.kind = kDense;
    } else {
      int32_t hdr[7];
      if (std::fread(hdr, 4, 7, f) != 7 || hdr[1] <= 0 || hdr[2] <= 0) {
        std::fclose(f);
        return false;
      }
      l.kind = hdr[0];
      l.in_dim = hdr[1];
      l.out_dim = hdr[2];
      l.in_h = hdr[3];
      l.in_w = hdr[4];
      l.in_c = hdr[5];
      l.out_c = hdr[6];
      // wire data is untrusted: geometry must be internally consistent or
      // conv_pool_forward would read out of bounds
      bool ok;
      if (l.kind == kConv3x3Pool) {
        ok = l.in_h > 0 && l.in_w > 0 && l.in_c > 0 && l.out_c > 0 &&
             l.in_h % 2 == 0 && l.in_w % 2 == 0 &&
             static_cast<int64_t>(l.in_h) * l.in_w * l.in_c == l.in_dim &&
             static_cast<int64_t>(l.in_h / 2) * (l.in_w / 2) * l.out_c == l.out_dim &&
             static_cast<int64_t>(9) * l.in_c * l.out_c < (1 << 28);
      } else {
        ok = l.kind == kDense &&
             static_cast<int64_t>(l.in_dim) * l.out_dim < (1 << 28);
      }
      if (!ok) {
        std::fclose(f);
        return false;
      }
    }
  }
  for (auto &l : layers) {
    size_t wsize = l.kind == kConv3x3Pool
                       ? static_cast<size_t>(9) * l.in_c * l.out_c
                       : static_cast<size_t>(l.in_dim) * l.out_dim;
    size_t bsize = l.kind == kConv3x3Pool ? static_cast<size_t>(l.out_c)
                                          : static_cast<size_t>(l.out_dim);
    l.w.assign(wsize, 0.0f);
    l.b.assign(bsize, 0.0f);
    if (std::fread(l.w.data(), sizeof(float), l.w.size(), f) != l.w.size() ||
        std::fread(l.b.data(), sizeof(float), l.b.size(), f) != l.b.size()) {
      std::fclose(f);
      return false;
    }
  }
  std::fclose(f);
  return true;
}

DenseModel DenseModel::create(const std::vector<int> &dims, uint64_t seed) {
  DenseModel m;
  uint64_t state = seed * 0x9E3779B97F4A7C15ULL + 1;
  for (size_t i = 0; i + 1 < dims.size(); ++i) {
    DenseLayer l;
    l.kind = kDense;
    l.in_dim = dims[i];
    l.out_dim = dims[i + 1];
    l.w.resize(static_cast<size_t>(l.in_dim) * l.out_dim);
    l.b.assign(l.out_dim, 0.0f);
    init_weights(l, state, l.in_dim);
    m.layers.push_back(std::move(l));
  }
  return m;
}

DenseModel DenseModel::create_conv(int in_h, int in_w, int in_c,
                                   const std::vector<int> &conv_channels,
                                   const std::vector<int> &dense_dims, uint64_t seed) {
  DenseModel m;
  uint64_t state = seed * 0x9E3779B97F4A7C15ULL + 5;
  int h = in_h, w = in_w, c = in_c;
  for (int oc : conv_channels) {
    if (h % 2 || w % 2 || h <= 0 || w <= 0 || oc <= 0)
      return m;  // empty model = invalid spec (caller checks layers.empty())
    DenseLayer l;
    l.kind = kConv3x3Pool;
    l.in_h = h;
    l.in_w = w;
    l.in_c = c;
    l.out_c = oc;
    l.in_dim = h * w * c;
    l.out_dim = (h / 2) * (w / 2) * oc;
    l.w.resize(static_cast<size_t>(9) * c * oc);
    l.b.assign(oc, 0.0f);
    init_weights(l, state, 9 * c);
    m.layers.push_back(std::move(l));
    h /= 2;
    w /= 2;
    c = oc;
  }
  int prev = h * w * c;
  for (int d : dense_dims) {
    DenseLayer l;
    l.kind = kDense;
    l.in_dim = prev;
    l.out_dim = d;
    l.w.resize(static_cast<size_t>(prev) * d);
    l.b.assign(d, 0.0f);
    init_weights(l, state, prev);
    m.layers.push_back(std::move(l));
    prev = d;
  }
  return m;
}

}  // namespace fedml_edge
