// FedMLClientManager: the object the mobile/Java (or Python-ctypes) layer
// drives. Reference: android/fedmlsdk/MobileNN/src/FedMLClientManager.cpp and
// includes/FedMLClientManager.h:6 — owns a trainer, forwards
// init/train/getEpochAndLoss/stopTraining.

#include "fedml_edge/client_manager.h"

namespace fedml_edge {

FedMLClientManager::FedMLClientManager() : trainer_(new FedMLDenseTrainer()) {}

FedMLClientManager::~FedMLClientManager() { delete trainer_; }

void FedMLClientManager::init(const char *model_cache_path, const char *data_cache_path,
                              const char *dataset, int train_size, int test_size,
                              int batch_size, double learning_rate, int epoch_num,
                              ProgressCallback progress_cb, AccuracyCallback accuracy_cb,
                              LossCallback loss_cb) {
  trainer_->init(model_cache_path, data_cache_path, dataset, train_size, test_size,
                 batch_size, learning_rate, epoch_num, std::move(progress_cb),
                 std::move(accuracy_cb), std::move(loss_cb));
}

std::string FedMLClientManager::train() { return trainer_->train(); }

std::string FedMLClientManager::get_epoch_and_loss() const {
  return trainer_->get_epoch_and_loss();
}

bool FedMLClientManager::stop_training() { return trainer_->stop_training(); }

FedMLDenseTrainer *FedMLClientManager::trainer() { return trainer_; }

}  // namespace fedml_edge
